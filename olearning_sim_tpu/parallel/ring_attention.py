"""Ring attention: sequence-parallel self-attention over a mesh axis.

Long-context path for the transformer family. The sequence axis is sharded
over a mesh axis (``sp``): each device holds a [B, H, L/P, D] chunk of
q/k/v. P ring steps rotate the K/V chunks (+their padding masks) around the
axis with ``jax.lax.ppermute`` while every device accumulates attention for
its local queries using the online-softmax merge (m, l, acc) — so the full
[L, L] score matrix never exists anywhere, per-device memory is O(L/P), and
the K/V transfers ride ICI neighbor links (a ring is exactly what ppermute
with a +1 rotation lays onto the torus).

Per-step local attention is either plain XLA ops (the default — measured
faster single-chip, see ``ops/flash_attention.py``) or the fused Pallas
kernel (``use_flash=True``; per-chunk scores stay in VMEM; trainable —
the kernel carries a custom VJP that rematerializes the backward through
XLA). ``scripts/bench_ring_step.py`` measures the two at ring-chunk
shapes.

Usage requires being inside ``shard_map`` with the sequence axis sharded
over ``axis_name`` — see ``ring_self_attention`` for the module-level entry.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from olearning_sim_tpu.utils.compat import ensure_jax_compat

# This module calls jax.shard_map; adapt legacy runtimes before first use.
ensure_jax_compat()


NEG_INF = -1e30


def _local_scores(q, k, scale):
    # [B, H, Lq, D] x [B, H, Lk, D] -> [B, H, Lq, Lk], f32 accumulation.
    return jax.lax.dot_general(
        q.astype(jnp.float32), k.astype(jnp.float32),
        (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) * scale


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array],
    axis_name: str,
    scale: Optional[float] = None,
    use_flash: bool = False,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Args (all per-device chunks, inside shard_map):
      q, k, v: [B, H, Lc, D] local chunks (global L = Lc * axis size).
      kv_mask: [B, Lc] bool, True = real key; None = no padding.
      use_flash: compute each ring step's local attention with the fused
        Pallas kernel (``ops.flash_attention_stats``) instead of plain XLA
        ops. Trainable (the kernel carries a custom VJP whose backward
        rematerializes through XLA) but default OFF: XLA's fused dense
        attention measured faster at every single-chip length tried (see
        ``ops/flash_attention.py``); flip the default only if
        ``scripts/bench_ring_step.py`` shows the kernel winning at your
        chunk shapes.
    Returns [B, H, Lc, D] — the local queries' attention over the GLOBAL
    sequence, in q's dtype.
    """
    B, H, Lc, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    p = jax.lax.psum(1, axis_name)
    if kv_mask is None:
        kv_mask = jnp.ones((B, Lc), bool)
    perm = [(i, (i + 1) % p) for i in range(p)]

    qf = q.astype(jnp.float32)
    # Accumulators derive from q (full_like/zeros_like) so their varying-
    # manual-axes type matches the scan body's outputs under ANY enclosing
    # shard_map (sp alone, dp x sp, ...) — a pvary over just the ring axis
    # would mismatch when other manual axes are present.
    m0 = jnp.full_like(qf[..., :1], NEG_INF)
    l0 = jnp.zeros_like(qf[..., :1])
    acc0 = jnp.zeros_like(qf)

    def combine_dense(k_cur, v_cur, mask_cur, m, l, acc):
        s = _local_scores(qf, k_cur, scale)                    # [B,H,Lc,Lck]
        s = s + jnp.where(mask_cur, 0.0, NEG_INF)[:, None, None, :]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        # Fully-masked-so-far rows keep m at NEG_INF; pin the shift to 0 so
        # exp() underflows instead of producing exp(0)=1 garbage.
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        alpha = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - shift)
        pij = jnp.exp(s - shift)
        l_new = alpha * l + jnp.sum(pij, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            pij, v_cur.astype(jnp.float32),
            (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    def combine_flash(k_cur, v_cur, mask_cur, m, l, acc):
        # The kernel returns this block's normalized output + its softmax
        # stats; fold it into the running (m, l, acc) exactly. Fully-masked
        # rows come back as (o=0, m=0, l=0): beta * l_blk = 0, and the m
        # overestimate rescales l and acc identically, so acc/l is intact.
        from olearning_sim_tpu.ops.flash_attention import flash_attention_stats

        o_blk, m_blk, l_blk = flash_attention_stats(
            q, k_cur, v_cur, kv_mask=mask_cur, scale=scale
        )
        m_blk = m_blk[..., None]                     # [B,H,Lc,1] f32
        l_blk = l_blk[..., None]
        m_new = jnp.maximum(m, m_blk)
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        alpha = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - shift)
        beta = jnp.exp(jnp.where(l_blk > 0, m_blk, NEG_INF) - shift)
        l_new = alpha * l + beta * l_blk
        acc_new = alpha * acc + beta * (o_blk.astype(jnp.float32) * l_blk)
        return m_new, l_new, acc_new

    combine = combine_flash if use_flash else combine_dense

    def step(carry, _):
        k_cur, v_cur, mask_cur, m, l, acc = carry
        m_new, l_new, acc_new = combine(k_cur, v_cur, mask_cur, m, l, acc)
        # Rotate K/V (and their padding mask) one hop around the ring.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        return (k_nxt, v_nxt, mask_nxt, m_new, l_new, acc_new), None

    carry, _ = jax.lax.scan(step, (k, v, kv_mask, m0, l0, acc0), None, length=p)
    _, _, _, m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-20)
    return out.astype(q.dtype)


class RingSelfAttention(nn.Module):
    """Drop-in MHA replacement whose sequence axis is sharded over
    ``axis_name`` (the model's ``attention_impl='ring'`` path,
    ``models/transformer.py``). Must be applied inside shard_map with the
    L axis of its input sharded on that mesh axis; projections are local
    (per-token), so only attention itself communicates.

    Parameter-compatible with ``nn.MultiHeadDotProductAttention``
    (submodules ``query``/``key``/``value`` with kernels [W, H, D] and
    ``out`` with kernel [H, D, W]) — a model trained with dense attention
    applies unchanged with ``attention_impl='ring'`` for long-context
    inference/eval.
    """

    num_heads: int
    axis_name: str = "sp"
    dtype: jnp.dtype = jnp.bfloat16
    use_flash: bool = False  # see ring_attention(use_flash=); trainable

    @nn.compact
    def __call__(self, x: jax.Array, pad_mask: jax.Array) -> jax.Array:
        # x: [B, Lc, W] local chunk; pad_mask: [B, Lc].
        B, Lc, W = x.shape
        head_dim = W // self.num_heads
        proj = lambda name: nn.DenseGeneral(
            features=(self.num_heads, head_dim), axis=-1, dtype=self.dtype,
            name=name,
        )
        q, k, v = (
            jnp.moveaxis(proj(n)(x), 2, 1)         # [B, H, Lc, D]
            for n in ("query", "key", "value")
        )
        o = ring_attention(q, k, v, pad_mask, self.axis_name,
                           use_flash=self.use_flash)
        o = jnp.moveaxis(o, 1, 2)                  # [B, Lc, H, D]
        return nn.DenseGeneral(
            features=W, axis=(-2, -1), dtype=self.dtype, name="out"
        )(o)

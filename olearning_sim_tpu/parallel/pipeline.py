"""Pipeline parallelism (``pp``): GPipe-style stage pipelining of the text
family over a mesh axis.

Completes the rebuild's parallelism set — ``dp`` (clients), ``mp``
(tensor), ``sp`` (sequence), ``ep`` (experts), ``pp`` (layers). The
reference has none of these axes (SURVEY.md section 2.5).

Design (manual ``shard_map`` over ``pp``, dp composes as a batch axis):

- the transformer's blocks are stacked into one ``[depth, ...]`` pytree
  (every block shares a treedef) and the stage axis is sharded over ``pp``:
  each device owns ``depth / pp`` consecutive blocks;
- the batch is split into M microbatches; a ``lax.scan`` over
  ``M + pp - 1`` ticks streams them through the stages, rotating
  activations stage-to-stage with ``ppermute`` (neighbor hops on the ICI
  torus). Stage 0 feeds embeddings in; the last stage collects block
  outputs; head/pooling run on the collected stream and the logits are
  summed across stages (only the last stage contributes non-zero);
- parameters are the DENSE model's — :func:`stack_block_params` /
  :func:`unstack_block_params` convert between the per-name layout
  (``TransformerBlock_i``) and the stacked stage layout, so params trained
  densely pipeline unchanged (and vice versa).

``pp_forward(model, params, tokens, plan)`` matches
``model.apply(params, tokens)`` (dense, single device) exactly up to bf16
reduction order — asserted in ``tests/test_pipeline.py``.
"""

from __future__ import annotations

import re
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from olearning_sim_tpu.parallel.mesh import MeshPlan, global_put

from olearning_sim_tpu.utils.compat import ensure_jax_compat

# This module calls jax.shard_map; adapt legacy runtimes before first use.
ensure_jax_compat()


_BLOCK_RE = re.compile(r"^TransformerBlock_(\d+)$")


def stack_block_params(params: Any) -> Tuple[Any, Any]:
    """Split a dense TextTransformer param tree into (rest, stacked_blocks)
    where ``stacked_blocks`` has every leaf led by a ``depth`` axis."""
    blocks = {}
    rest = {}
    for name, sub in params.items():
        m = _BLOCK_RE.match(name)
        if m:
            blocks[int(m.group(1))] = sub
        else:
            rest[name] = sub
    if not blocks:
        raise ValueError("no TransformerBlock_i entries in params")
    ordered = [blocks[i] for i in range(len(blocks))]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *ordered)
    return rest, stacked


def unstack_block_params(rest: Any, stacked: Any) -> Any:
    """Inverse of :func:`stack_block_params`."""
    depth = jax.tree.leaves(stacked)[0].shape[0]
    out = dict(rest)
    for i in range(depth):
        out[f"TransformerBlock_{i}"] = jax.tree.map(lambda x: x[i], stacked)
    return out


def _validate_pp_inputs(model, plan: MeshPlan, caller: str, tokens,
                        num_microbatches) -> int:
    """Validate and return the resolved microbatch count M."""
    if plan.pp <= 1:
        raise ValueError(
            f"{caller} needs a mesh with a pp axis (make_mesh_plan(pp=...))"
        )
    if model.depth % plan.pp:
        raise ValueError(
            f"pp={plan.pp} must divide the model depth {model.depth}"
        )
    impl = getattr(model, "attention_impl", "dense")
    if impl != "dense":
        # The stage blocks apply dense attention. Ring params are
        # layout-compatible, but the ring forward needs an sp axis inside
        # shard_map (sharded sequence + psum pooling) which the pipeline
        # graph doesn't provide; flash additionally has a different param
        # layout. Fail at the boundary, not inside scan.
        raise ValueError(
            f"pipeline parallelism requires attention_impl='dense', the "
            f"model was built with {impl!r}"
        )
    M = num_microbatches if num_microbatches is not None else plan.pp
    if M <= 0:
        raise ValueError(f"num_microbatches must be positive, got {M}")
    B = np.asarray(tokens).shape[0]
    if B % (plan.dp * M):
        raise ValueError(
            f"dp*num_microbatches = {plan.dp}*{M} must divide the batch {B} "
            f"(microbatching applies to each dp shard's local batch)"
        )
    return M


def _microbatch(tokens, num_microbatches: int):
    B = tokens.shape[0]
    if B % num_microbatches:
        raise ValueError(
            f"num_microbatches={num_microbatches} must divide the batch {B}"
        )
    mb = B // num_microbatches
    return tokens.reshape((num_microbatches, mb) + tokens.shape[1:])


def pp_forward(model, params, tokens, plan: MeshPlan,
               num_microbatches: int = None):
    """Forward the dense-attention text ``model`` with its blocks pipelined
    over the plan's ``pp`` axis. Returns logits [B, num_classes], matching
    the dense ``model.apply`` on one device."""
    M = _validate_pp_inputs(model, plan, "pp_forward", tokens,
                            num_microbatches)
    if isinstance(params, tuple):
        # Pre-placed (rest, stacked) from pp_place_params — no host
        # round-trip of the block weights.
        rest, stacked = params
    else:
        rest, stacked = pp_place_params(params, plan)
    return _compiled_forward(model, plan.mesh, M)(
        rest, stacked, global_put(np.asarray(tokens),
                                  NamedSharding(plan.mesh, P("dp"))),
    )


_FWD_CACHE: dict = {}


def _compiled_forward(model, mesh, M: int):
    key = (model, mesh, M)
    if key not in _FWD_CACHE:
        _FWD_CACHE[key] = _build(model, mesh, M)
    return _FWD_CACHE[key]


def _build(model, mesh, M: int):
    pipeline = _PipelineGraph(model, mesh, M)

    def body(rest, local_blocks, tokens):
        return pipeline.logits(rest, local_blocks, tokens)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P("pp"), P("dp")),
            out_specs=P("dp"),
            axis_names=frozenset({"dp", "pp"}),
            check_vma=False,
        )
    )


# ------------------------------------------------------------------ training
def pp_place_params(params: Any, plan: MeshPlan) -> Tuple[Any, Any]:
    """Split and place dense params for pipelined training: returns
    ``(rest, stacked)`` with the block stack's leading depth axis sharded
    over ``pp`` and everything else replicated."""
    if plan.pp <= 1:
        raise ValueError(
            "pp_place_params needs a mesh with a pp axis (make_mesh_plan(pp=...))"
        )
    rest, stacked = stack_block_params(params)
    rest = jax.tree.map(
        lambda x: global_put(np.asarray(x), NamedSharding(plan.mesh, P())),
        rest,
    )
    stacked = jax.tree.map(
        lambda x: global_put(np.asarray(x), NamedSharding(plan.mesh, P("pp"))),
        stacked,
    )
    return rest, stacked


_GRAD_CACHE: dict = {}
_APPLY_CACHE: dict = {}


def pp_train_step(model, rest, stacked, opt_state, tokens, labels, optimizer,
                  plan: MeshPlan, num_microbatches: int = None):
    """One optimizer step with the block stack pipelined over ``pp``.

    Block gradients are computed stage-local (each stage only differentiates
    through its own layers — they stay sharded over ``pp``); embed/head
    gradients are partial per stage and are psum'd. The optimizer update
    runs in a follow-up GSPMD-auto jit so optimizer-state shardings follow
    the params they track.

    Contract: ``rest``/``stacked``/``opt_state`` are DONATED; reuse one
    optimizer instance across steps (compiled steps cached per
    (model, mesh, microbatches)). Returns
    ``(rest, stacked, opt_state, loss)``.
    """
    M = _validate_pp_inputs(model, plan, "pp_train_step", tokens,
                            num_microbatches)
    tokens = global_put(np.asarray(tokens), NamedSharding(plan.mesh, P("dp")))
    labels = global_put(np.asarray(labels), NamedSharding(plan.mesh, P("dp")))

    key = (model, plan.mesh, M)
    if key not in _GRAD_CACHE:
        _GRAD_CACHE[key] = _build_grads(model, plan.mesh, M)
    loss, g_rest, g_blocks = _GRAD_CACHE[key](rest, stacked, tokens, labels)

    # Cache holds a strong reference to the optimizer and compares object
    # identity — an id() comparison could silently match a recycled address
    # after the original optimizer is garbage-collected.
    cached = _APPLY_CACHE.get(key)
    if cached is None or cached[0] is not optimizer:
        def apply(params, opt_state, grads):
            updates, new_opt = optimizer.update(grads, opt_state, params)
            import optax as _optax

            return _optax.apply_updates(params, updates), new_opt

        _APPLY_CACHE[key] = (optimizer, jax.jit(apply, donate_argnums=(0, 1)))
        cached = _APPLY_CACHE[key]
    (rest, stacked), opt_state = cached[1](
        (rest, stacked), opt_state, (g_rest, g_blocks)
    )
    return rest, stacked, opt_state, loss


def _build_grads(model, mesh, M: int):
    import optax

    from olearning_sim_tpu.parallel.scale_check import verify_grad_scale

    # The /scale division below encodes an empirical JAX transpose behavior;
    # measure it on a one-scalar program first and refuse to train if it
    # moved (e.g. after a JAX upgrade) — see parallel/scale_check.py.
    verify_grad_scale(mesh, ("dp", "pp"))
    pipeline = _PipelineGraph(model, mesh, M)

    def body(rest, local_blocks, tokens, labels):
        def loss_fn(r, lb):
            logits = pipeline.logits(r, lb, tokens)
            local = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return jax.lax.pmean(local, "dp")

        loss, (g_rest, g_blocks) = jax.value_and_grad(
            loss_fn, argnums=(0, 1)
        )(rest, local_blocks)
        # With check_vma=False every psum/pmean transposes to psum, so the
        # replicated loss cotangent enters the backward once per stage —
        # each device's gradient is uniformly pp x its true partial
        # (verified empirically leaf by leaf, see tests). Blocks are
        # stage-local shards whose dp-partials must sum; embed/head
        # partials sum across both axes.
        scale = jax.lax.psum(1, "pp") * jax.lax.psum(1, "dp")
        g_rest = jax.lax.psum(g_rest, ("dp", "pp"))
        g_rest = jax.tree.map(lambda g: g / scale, g_rest)
        g_blocks = jax.lax.psum(g_blocks, "dp")
        g_blocks = jax.tree.map(lambda g: g / scale, g_blocks)
        return loss, g_rest, g_blocks

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P("pp"), P("dp"), P("dp")),
            out_specs=(P(), P(), P("pp")),
            axis_names=frozenset({"dp", "pp"}),
            check_vma=False,
        )
    )


class _PipelineGraph:
    """The pipelined logits computation, shared by forward and training
    (identical graph; ``_build``'s body wraps it for inference).

    COUPLING NOTE: ``embed``/``head`` mirror TextTransformer.__call__'s
    prologue/epilogue by flax auto-generated param name (Embed_0 /
    pos_embedding / LayerNorm_0 / Dense_0) — restructuring the dense model
    into setup()-style methods would rename every param and break existing
    checkpoints, so the mirror is kept and
    ``test_pp_forward_matches_dense`` enforces it stays in sync."""

    def __init__(self, model, mesh, M: int):
        self.model = model
        self.pp = mesh.shape["pp"]
        self.M = M

        from olearning_sim_tpu.models.transformer import TransformerBlock

        self.blk = TransformerBlock(
            model.width, model.heads, model.mlp_dim, model.dtype, "dense"
        )

    def embed(self, rest, toks):
        model = self.model
        emb = nn.Embed(
            model.vocab_size, model.width, param_dtype=jnp.float32,
        ).apply({"params": rest["Embed_0"]}, toks)
        L = toks.shape[1]
        x = (emb + rest["pos_embedding"][:, :L]).astype(model.dtype)
        return nn.LayerNorm(dtype=model.dtype).apply(
            {"params": rest["LayerNorm_0"]}, x
        )

    def head(self, rest, x, pad_mask):
        m = pad_mask[..., None].astype(jnp.float32)
        s = (x.astype(jnp.float32) * m).sum(1)
        c = m.sum(1)
        pooled = s / jnp.maximum(c, 1.0)
        return nn.Dense(self.model.num_classes, dtype=jnp.float32).apply(
            {"params": rest["Dense_0"]}, pooled
        )

    def logits(self, rest, local_blocks, tokens):
        model, M, pp = self.model, self.M, self.pp
        stage = jax.lax.axis_index("pp")
        toks_mb = _microbatch(tokens, M)
        pad_mb = toks_mb != model.pad_id
        emb_mb = jax.vmap(lambda t: self.embed(rest, t))(toks_mb)

        mb, L, W = emb_mb.shape[1:]
        total = M + pp - 1
        perm = [(i, i + 1) for i in range(pp - 1)]

        def stage_apply(x, pad_mask):
            def one(c, bp):
                return self.blk.apply({"params": bp}, c, pad_mask), None

            x, _ = jax.lax.scan(one, x, local_blocks)
            return x

        def tick(carry, t):
            recv, outs = carry
            feed_idx = jnp.clip(t, 0, M - 1)
            x0 = jnp.where(t < M, emb_mb[feed_idx], jnp.zeros_like(emb_mb[0]))
            xin = jnp.where(stage == 0, x0, recv)
            mb_idx = jnp.clip(t - stage, 0, M - 1)
            y = stage_apply(xin, pad_mb[mb_idx])
            sent = jax.lax.ppermute(y, "pp", perm)
            out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
            valid = (t >= pp - 1) & (stage == pp - 1)
            outs = jnp.where(
                valid,
                jax.lax.dynamic_update_index_in_dim(outs, y, out_idx, 0),
                outs,
            )
            return (sent, outs), None

        outs0 = jnp.zeros((M, mb, L, W), emb_mb.dtype)
        (_, outs), _ = jax.lax.scan(
            tick, (jnp.zeros((mb, L, W), emb_mb.dtype), outs0),
            jnp.arange(total),
        )
        logits = jax.vmap(lambda x, m: self.head(rest, x, m))(outs, pad_mb)
        logits = jnp.where(stage == pp - 1, logits, jnp.zeros_like(logits))
        logits = jax.lax.psum(logits, "pp")
        return logits.reshape((M * mb,) + logits.shape[2:])

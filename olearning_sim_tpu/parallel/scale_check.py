"""Build-time self-check of the ``check_vma=False`` gradient-transpose factor.

The sp/pp train steps (``long_context.py``, ``pipeline.py``) compile their
bodies with ``shard_map(..., check_vma=False)`` because the default VMA
bookkeeping inserts copy-computation all-reduces that crash XLA-CPU's
AllReducePromotion pass. Under that flag, ``psum``/``pmean`` transpose to
``psum`` in the backward pass, so the gradient of a replicated parameter
comes out uniformly inflated by the product of the mesh axis sizes — and
both train steps divide by exactly that factor.

That factor is an empirical property of JAX's transpose rules, not a
contract: a JAX upgrade that changes VMA handling would silently change it
on TPU, where the CPU equivalence tests that pin it today don't run
(VERDICT r2 weak #3). So every train-step build first measures the factor
on a one-scalar problem compiled with the SAME shard_map structure and
refuses to run if it moved. Costs one tiny compile per (mesh, axes) per
process.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from olearning_sim_tpu.utils.compat import ensure_jax_compat

# This module calls jax.shard_map; adapt legacy runtimes before first use.
ensure_jax_compat()


_CHECKED: set = set()


def expected_factor(mesh, axes: Tuple[str, ...]) -> int:
    """The inflation factor the sp/pp train steps currently divide by."""
    return math.prod(int(mesh.shape[a]) for a in axes)


def measured_factor(mesh, axes: Tuple[str, ...]) -> float:
    """Measure the backward inflation of a replicated scalar through
    ``pmean(., first_axis)`` under ``check_vma=False`` — the exact loss
    structure of the sp/pp train steps."""
    reduce_axis = axes[0]

    def body(w):
        def loss_fn(w):
            return jax.lax.pmean(w * 1.0, reduce_axis)

        g = jax.grad(loss_fn)(w)
        return jax.lax.psum(g, axes)

    fn = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(),),
            out_specs=P(),
            axis_names=frozenset(axes),
            check_vma=False,
        )
    )
    # Dense reference: loss(w) == w, so d loss/d w == 1 and the returned
    # cross-device gradient sum IS the inflation factor.
    return float(fn(jnp.float32(1.0)))


def verify_grad_scale(mesh, axes: Tuple[str, ...]) -> None:
    """Fail fast (RuntimeError) if the check_vma=False transpose behavior no
    longer matches the hardcoded gradient scale in the sp/pp train steps."""
    key = (
        tuple(sorted((a, int(mesh.shape[a])) for a in axes)),
        getattr(mesh.devices.flat[0], "platform", "?"),
    )
    if key in _CHECKED:
        return
    want = expected_factor(mesh, axes)
    got = measured_factor(mesh, axes)
    if abs(got - want) > 1e-6 * max(1.0, abs(want)):
        raise RuntimeError(
            f"check_vma=False gradient-transpose factor changed: measured "
            f"{got} but the train steps divide by {want} (mesh axes "
            f"{dict((a, int(mesh.shape[a])) for a in axes)}, jax "
            f"{jax.__version__}). A JAX upgrade likely altered psum/pmean "
            f"transposition under check_vma=False — re-derive the scale in "
            f"parallel/pipeline.py and parallel/long_context.py before "
            f"training with sp/pp."
        )
    _CHECKED.add(key)

"""Expert parallelism (``ep``): shard MoE expert weights over a mesh axis
and let GSPMD insert the token all-to-alls.

Counterpart to :mod:`olearning_sim_tpu.parallel.tp` (tensor parallelism,
``mp``) and :mod:`olearning_sim_tpu.parallel.long_context` (sequence
parallelism, ``sp``). The reference has none of these axes (SURVEY.md
section 2.5); MoE/expert parallelism is the rebuild's third model-scaling
axis, for the :class:`~olearning_sim_tpu.models.moe.MoETextTransformer`
family.

Design (pure GSPMD auto mode — no shard_map): every per-expert leaf (leading
dim == num_experts, names ``expert_*`` from :class:`SwitchFFN`) is annotated
``PartitionSpec("ep", ...)``; the batch is sharded over ``dp``. XLA then
places each device's expert shard locally and inserts all-to-alls moving
token slots to their experts' devices and back — exactly the hand-written
MoE dispatch of GShard/Switch, derived from shardings instead of coded.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from olearning_sim_tpu.parallel.mesh import MeshPlan, global_put
from olearning_sim_tpu.parallel.tp import _path_str, sharded_fraction

from olearning_sim_tpu.utils.compat import ensure_jax_compat

# This module calls jax.shard_map; adapt legacy runtimes before first use.
ensure_jax_compat()


_EXPERT_PREFIX = "expert_"

# Same "fraction of elements on sharded leaves" metric as tensor
# parallelism; for ep specs only expert leaves carry a non-None axis.
sharded_expert_fraction = sharded_fraction


def ep_param_specs(params: Any, ep: int) -> Any:
    """PartitionSpec tree: per-expert leaves (``expert_*`` with a leading
    expert dim divisible by ``ep``) shard that dim over ``ep``; everything
    else replicated."""

    def rule(path, leaf):
        names = _path_str(path)
        if names and names[-1].startswith(_EXPERT_PREFIX):
            shape = getattr(leaf, "shape", ())
            if shape and shape[0] % ep == 0:
                return P("ep", *([None] * (len(shape) - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(rule, params)


def ep_place_params(params: Any, plan: MeshPlan) -> Any:
    """Place a params tree per :func:`ep_param_specs` on the plan's mesh."""
    if plan.ep <= 1:
        raise ValueError(
            "ep_place_params needs a mesh with an ep axis (make_mesh_plan(ep=...))"
        )
    specs = ep_param_specs(params, plan.ep)
    from olearning_sim_tpu.parallel.tp import warn_if_unsharded

    warn_if_unsharded(params, specs, plan.ep, axis="ep")
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(plan.mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P),
    ), specs


_TRAIN_CACHE: dict = {}


def ep_train_step(model, params, opt_state, tokens, labels, optimizer,
                  plan: MeshPlan, aux_weight: float = 0.01):
    """One optimizer step on a MoE text model with experts sharded over
    ``ep`` and the batch over ``dp`` (GSPMD auto mode — XLA derives the
    token all-to-alls from the weight shardings).

    The Switch load-balancing auxiliary loss (sown by :class:`SwitchFFN`)
    is added with weight ``aux_weight``. Returns
    ``(new_params, new_opt_state, loss)``; params keep their ep shardings.

    Contract: ``params``/``opt_state`` are DONATED (the input arrays are
    consumed — keep using the returned ones), and the caller must reuse ONE
    optimizer instance across steps: the compiled step is cached per
    (model, mesh, aux_weight) keyed on the optimizer's identity, so a fresh
    ``optax.sgd(...)`` per call recompiles every step."""
    if plan.ep <= 1:
        raise ValueError(
            "ep_train_step needs a mesh with an ep axis (make_mesh_plan(ep=...))"
        )
    B = tokens.shape[0]
    if B % plan.dp:
        raise ValueError(f"dp={plan.dp} must divide the batch {B}")
    tokens = global_put(np.asarray(tokens), NamedSharding(plan.mesh, P("dp")))
    labels = global_put(np.asarray(labels), NamedSharding(plan.mesh, P("dp")))
    return _compiled_step(model, plan, optimizer, aux_weight)(
        params, opt_state, tokens, labels
    )


def _compiled_step(model, plan: MeshPlan, optimizer, aux_weight: float):
    key = (model, plan.mesh, aux_weight)
    cached = _TRAIN_CACHE.get(key)
    # Strong reference + identity check (id() could match a recycled
    # address after GC of the original optimizer).
    if cached is not None and cached[0] is optimizer:
        return cached[1]

    def step(params, opt_state, tokens, labels):
        def loss_fn(p):
            logits, inter = model.apply(
                {"params": p}, tokens, mutable=["intermediates"]
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            # Mean of the per-block Switch aux losses (each sown as a
            # 1-tuple under intermediates).
            aux_vals = jax.tree.leaves(inter["intermediates"])
            aux_loss = (
                sum(jax.numpy.asarray(a).sum() for a in aux_vals)
                / max(len(aux_vals), 1)
            )
            return ce + aux_weight * aux_loss, ce

        (loss, ce), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, ce

    fn = jax.jit(step, donate_argnums=(0, 1))
    _TRAIN_CACHE[key] = (optimizer, fn)
    return fn

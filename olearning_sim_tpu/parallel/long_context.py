"""Long-context sequence parallelism: run and train the text family over
sequences too long for one device's HBM.

The per-client FL path keeps dense attention (device-class models see short
sequences — SURVEY.md section 5: client count, not sequence length, is the
platform's scaling axis). This module is the reachable surface for the
long-context machinery (:mod:`ring_attention`): forward/eval
(:func:`sp_forward` / :func:`sp_evaluate`) and centralized training
(:func:`sp_train_step`) of a global model over arbitrarily long inputs,
with the sequence axis sharded over the mesh ``sp`` axis and K/V chunks
rotating around the ring with ``ppermute`` — per-device attention memory is
O(L/sp) in forward AND backward, and the transfers ride ICI neighbor links.

Because :class:`RingSelfAttention` is parameter-compatible with the dense
path, the SAME params trained with ``attention_impl="dense"`` evaluate here
unchanged (and vice versa: one sp training step lands on the same params as
a dense step on the same global batch).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from olearning_sim_tpu.parallel.mesh import MeshPlan, global_put

from olearning_sim_tpu.utils.compat import ensure_jax_compat

# This module calls jax.shard_map; adapt legacy runtimes before first use.
ensure_jax_compat()


def _validate_sp_inputs(model, tokens, plan: MeshPlan, caller: str) -> None:
    if plan.sp <= 1:
        raise ValueError(
            f"{caller} needs a mesh with an sp axis (make_mesh_plan(sp=...))"
        )
    B, L = tokens.shape
    if L % plan.sp:
        raise ValueError(
            f"sp={plan.sp} must divide the sequence length {L}; pad the "
            f"sequences (pad_id tokens are masked out)"
        )
    if B % plan.dp:
        raise ValueError(f"dp={plan.dp} must divide the batch {B}")
    max_len = getattr(model, "max_len", None)
    if max_len is not None and L > max_len:
        # The ring path's positional dynamic_slice would clamp out-of-range
        # offsets and silently reuse early positions.
        raise ValueError(
            f"global sequence length {L} exceeds the model's max_len "
            f"{max_len}; build the model with max_len >= {L}"
        )


def sp_forward(model, params, tokens, plan: MeshPlan):
    """Forward the text ``model`` (built with ``attention_impl="ring"``)
    over ``tokens`` [B, L] with L sharded over the plan's ``sp`` axis and
    the batch over ``dp``. Returns logits [B, num_classes].

    ``sp`` must divide ``L`` and ``dp`` must divide ``B`` (pad with the
    model's pad_id / duplicate rows if not — padding tokens are masked out
    of attention and pooling by construction).
    """
    _validate_sp_inputs(model, tokens, plan, "sp_forward")
    tokens = global_put(
        np.asarray(tokens), NamedSharding(plan.mesh, P("dp", "sp"))
    )
    return _compiled_forward(model, plan.mesh)(params, tokens)


# flax Modules and Meshes hash by value, so identical (model, mesh) pairs
# reuse the compiled program across calls (sp_evaluate loops batches —
# rebuilding the jit closure per call would retrace and recompile every
# time).
_FWD_CACHE: dict = {}


def _compiled_forward(model, mesh):
    key = (model, mesh)
    if key not in _FWD_CACHE:
        def body(params, tokens_chunk):
            # logits are replicated over sp after the model's pooling psum.
            return model.apply({"params": params}, tokens_chunk)

        _FWD_CACHE[key] = jax.jit(
            jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), P("dp", "sp")),
                out_specs=P("dp"),
                axis_names=frozenset({"dp", "sp"}),
            )
        )
    return _FWD_CACHE[key]


def sp_train_step(model, params, opt_state, tokens, labels, optimizer,
                  plan: MeshPlan):
    """One optimizer step on a text model with the sequence sharded over
    ``sp`` (ring attention) and the batch over ``dp``.

    Differentiation goes straight through the ring: ``ppermute`` and the
    online-softmax merge are plain XLA ops, so ``jax.grad`` of the chunked
    loss is the exact gradient of the dense loss — per-device activation
    memory stays O(L/sp) in the backward pass too (the [L, L] score matrix
    never materializes). Gradients are psum'd over BOTH mesh axes (dp batch
    shards + sp sequence chunks) before the replicated optimizer update.

    Returns ``(new_params, new_opt_state, loss)`` with params/opt_state
    replicated — shapes and semantics match a single-device
    ``optimizer.update`` step on the same global batch.
    """
    _validate_sp_inputs(model, tokens, plan, "sp_train_step")
    tokens = global_put(
        np.asarray(tokens), NamedSharding(plan.mesh, P("dp", "sp"))
    )
    labels = global_put(
        np.asarray(labels), NamedSharding(plan.mesh, P("dp"))
    )
    return _compiled_train(model, plan.mesh, optimizer)(
        params, opt_state, tokens, labels
    )


_TRAIN_CACHE: dict = {}


def _compiled_train(model, mesh, optimizer):
    # optax transforms are closures without value hashing — track the
    # optimizer by identity, but key the cache on (model, mesh) only and
    # REPLACE on optimizer change: a caller constructing optax.sgd(...)
    # inline every step then pays a recompile per step (visible, fixable)
    # instead of silently growing an executable per call.
    key = (model, mesh)
    cached = _TRAIN_CACHE.get(key)
    # Strong reference + identity check (id() could match a recycled
    # address after GC of the original optimizer).
    if cached is not None and cached[0] is optimizer:
        return cached[1]

    import optax

    from olearning_sim_tpu.parallel.scale_check import verify_grad_scale

    # The grads pmean below encodes an empirical JAX transpose behavior;
    # measure it on a one-scalar program first and refuse to train if it
    # moved (e.g. after a JAX upgrade) — see parallel/scale_check.py.
    verify_grad_scale(mesh, ("dp", "sp"))

    def body(params, opt_state, tokens_chunk, labels_chunk):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens_chunk)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels_chunk
            ).mean()
            return jax.lax.pmean(loss, "dp")

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # With check_vma=False (below), psum/pmean transpose to psum — AD
        # inserts the cross-device reductions itself, so every device
        # already holds the FULL gradient and a further psum would multiply
        # it by the device count (verified empirically: per-leaf ratio vs
        # the dense single-device gradient is uniformly n_devices before
        # this pmean, 1.0 after).
        grads = jax.lax.pmean(grads, ("dp", "sp"))
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, loss

    # check_vma=False: the default VMA bookkeeping inserts copy-computation
    # all-reduces into the ring backward, and XLA-CPU's AllReducePromotion
    # pass crashes cloning them ("Invalid binary instruction opcode copy").
    # Replication of the outputs is established explicitly by the grads
    # pmean + replicated update.
    fn = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P("dp", "sp"), P("dp")),
            out_specs=(P(), P(), P()),
            axis_names=frozenset({"dp", "sp"}),
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )
    _TRAIN_CACHE[key] = (optimizer, fn)
    return fn


def sp_evaluate(model, params, tokens, labels, plan: MeshPlan,
                batch: Optional[int] = None) -> Tuple[float, float]:
    """Central eval (loss, accuracy) of a text model over long sequences,
    batched host-side."""
    import optax

    n = tokens.shape[0]
    if n == 0 or (batch is not None and batch <= 0):
        raise ValueError(
            f"sp_evaluate needs a non-empty eval set and positive batch "
            f"(n={n}, batch={batch})"
        )
    batch = batch or n
    batch += (-batch) % plan.dp
    # Pad the tail slice to the FULL batch (not just dp-divisibility): a
    # distinct tail shape would retrace and recompile the whole sharded
    # forward for one slice; padded rows are dropped via [:real] below.
    losses = accs = seen = 0.0
    for i in range(0, n, batch):
        tb, yb = tokens[i : i + batch], labels[i : i + batch]
        real = len(yb)
        pad = batch - real
        if pad:
            tb = np.concatenate([tb, np.repeat(tb[-1:], pad, 0)])
            yb = np.concatenate([yb, np.repeat(yb[-1:], pad, 0)])
        logits = jax.device_get(sp_forward(model, params, tb, plan))[:real]
        losses += float(
            optax.softmax_cross_entropy_with_integer_labels(
                jnp.asarray(logits), jnp.asarray(yb[:real])
            ).sum()
        )
        accs += float((logits.argmax(-1) == yb[:real]).sum())
        seen += real
    return losses / seen, accs / seen

from olearning_sim_tpu.parallel.mesh import (
    MeshPlan,
    make_mesh_plan,
    pad_to_multiple,
    shard_clients,
)

__all__ = ["MeshPlan", "make_mesh_plan", "pad_to_multiple", "shard_clients"]

"""Round-level performance accounting + profiler control.

Metrics of record (BASELINE.md): FL rounds/sec, device-rounds/sec (clients
advanced per wall-second), and per-client local-step latency. Timings are
host wall-clock around the compiled round step (device work is synchronized
by the runner's host transfer of the round loss, so the interval covers real
execution, not async dispatch).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Dict, List, Optional

from olearning_sim_tpu.utils.repo import MemoryTableRepo, TableRepo

PERF_COLUMNS = ["task_id", "round_idx", "operator", "duration_s",
                "num_clients", "local_steps", "extra"]


@dataclasses.dataclass
class RoundTiming:
    task_id: str
    round_idx: int
    operator: str
    duration_s: float
    num_clients: int = 0
    local_steps: int = 0
    # Actual total (client, step) pairs executed; overrides the
    # num_clients * local_steps estimate when heterogeneous compute profiles
    # give clients differing step counts.
    total_client_steps: int = 0
    extra: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def device_rounds_per_sec(self) -> float:
        return self.num_clients / self.duration_s if self.duration_s > 0 else 0.0

    @property
    def per_client_step_latency_s(self) -> float:
        """Amortized wall time per (client, local step) — the per-device-step
        cost the reference models as alpha=3.5 s/device-round on CPU actors
        (``utils_runner.py:941``)."""
        steps = self.total_client_steps or self.num_clients * max(self.local_steps, 1)
        return self.duration_s / steps if steps else 0.0


def _mean_step_latency(rows: List["RoundTiming"]) -> float:
    """Mean over client-advancing rows only: eval/custom rows (num_clients=0)
    contribute no steps and must not dilute the metric of record."""
    train_rows = [t for t in rows if t.num_clients > 0]
    if not train_rows:
        return 0.0
    return sum(t.per_client_step_latency_s for t in train_rows) / len(train_rows)


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class PerformanceManager:
    """Records timings, answers performance queries, controls the profiler."""

    def __init__(self, repo: Optional[TableRepo] = None, keep_last: int = 4096,
                 resilience_log=None):
        # No repo by default: queries are answered from the bounded in-memory
        # window. Pass a repo to persist every row for external analysis —
        # retention is then the caller's policy (rows are append-only).
        # ``resilience_log`` — the ResilienceLog whose counters get_resilience
        # reports; pass the runner's instance when it is not the process-
        # global default (ResilienceConfig(log=...)).
        self.repo = repo
        self.keep_last = keep_last
        self.resilience_log = resilience_log
        self._lock = threading.RLock()
        self._timings: Dict[str, List[RoundTiming]] = {}
        self._trace_dir: Optional[str] = None

    # ------------------------------------------------------------- recording
    def record_round(self, timing: RoundTiming) -> None:
        with self._lock:
            rows = self._timings.setdefault(timing.task_id, [])
            rows.append(timing)
            if len(rows) > self.keep_last:
                del rows[: len(rows) - self.keep_last]
            if self.repo is None:
                return
            self.repo.add_item({
                "task_id": [timing.task_id],
                "round_idx": [str(timing.round_idx)],
                "operator": [timing.operator],
                "duration_s": [repr(timing.duration_s)],
                "num_clients": [str(timing.num_clients)],
                "local_steps": [str(timing.local_steps)],
                # total_client_steps rides in the extra JSON (no schema change)
                # so heterogeneous-profile per-client step latency stays
                # recomputable from a persisted repo, not just in memory.
                "extra": [json.dumps(
                    {**timing.extra,
                     "total_client_steps": timing.total_client_steps}
                )],
            })

    class _Timer:
        def __init__(self, mgr: "PerformanceManager", task_id: str,
                     round_idx: int, operator: str, num_clients: int,
                     local_steps: int, total_client_steps: int):
            self._mgr = mgr
            self._args = (task_id, round_idx, operator, num_clients,
                          local_steps, total_client_steps)

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                task_id, round_idx, operator, nc, ls, tcs = self._args
                self._mgr.record_round(RoundTiming(
                    task_id=task_id, round_idx=round_idx, operator=operator,
                    duration_s=time.perf_counter() - self._t0,
                    num_clients=nc, local_steps=ls, total_client_steps=tcs,
                ))
            return False

    def time_round(self, task_id: str, round_idx: int, operator: str,
                   num_clients: int = 0, local_steps: int = 0,
                   total_client_steps: int = 0) -> "_Timer":
        """``with perf.time_round(...):`` around one operator execution."""
        return PerformanceManager._Timer(
            self, task_id, round_idx, operator, num_clients, local_steps,
            total_client_steps,
        )

    # --------------------------------------------------------------- queries
    def get_resilience(self, task_id: str) -> Dict[str, int]:
        """Resilience counters for one task (retries, rollbacks, quarantines,
        injected faults — olearning_sim_tpu.resilience.events). Part of the
        performance answer so robustness regressions ride the same query as
        throughput regressions."""
        log = self.resilience_log
        if log is None:
            from olearning_sim_tpu.resilience.events import global_log

            log = global_log()
        return log.counters(task_id)

    def get_performance(self, task_id: str) -> Dict[str, Any]:
        """Summary for one task: throughput + latency distribution
        (the ``PerformanceMgr.getPerformance`` answer)."""
        resilience = self.get_resilience(task_id)
        with self._lock:
            rows = list(self._timings.get(task_id, []))
        if not rows:
            return {"task_id": task_id, "rounds_recorded": 0,
                    "resilience": resilience}
        durations = sorted(t.duration_s for t in rows)
        total_time = sum(durations)
        total_clients = sum(t.num_clients for t in rows)
        distinct_rounds = len({t.round_idx for t in rows})
        return {
            "task_id": task_id,
            "rounds_recorded": distinct_rounds,
            "operator_executions": len(rows),
            "total_time_s": total_time,
            "rounds_per_sec": distinct_rounds / total_time if total_time else 0.0,
            "device_rounds_per_sec": total_clients / total_time if total_time else 0.0,
            "round_time_s": {
                "mean": total_time / len(durations),
                "p50": _percentile(durations, 0.50),
                "p95": _percentile(durations, 0.95),
                "max": durations[-1],
            },
            "per_client_step_latency_s": _mean_step_latency(rows),
            "resilience": resilience,
        }

    def list_tasks(self) -> List[str]:
        with self._lock:
            return sorted(self._timings)

    # -------------------------------------------------------------- profiler
    def start_trace(self, logdir: str) -> bool:
        """Begin a ``jax.profiler`` trace (XLA op-level timeline viewable in
        TensorBoard/Perfetto). One trace at a time."""
        import jax

        with self._lock:
            if self._trace_dir is not None:
                return False
            jax.profiler.start_trace(logdir)
            self._trace_dir = logdir
            return True

    def stop_trace(self) -> Optional[str]:
        import jax

        with self._lock:
            if self._trace_dir is None:
                return None
            jax.profiler.stop_trace()
            out, self._trace_dir = self._trace_dir, None
            return out

"""Round-level performance accounting + profiler control.

Metrics of record (BASELINE.md): FL rounds/sec, device-rounds/sec (clients
advanced per wall-second), and per-client local-step latency. Timings are
host wall-clock around the compiled round step (device work is synchronized
by the runner's host transfer of the round loss, so the interval covers real
execution, not async dispatch).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

from olearning_sim_tpu.utils.repo import MemoryTableRepo, TableRepo

PERF_COLUMNS = ["task_id", "round_idx", "operator", "duration_s",
                "num_clients", "local_steps", "extra"]


@dataclasses.dataclass
class RoundTiming:
    task_id: str
    round_idx: int
    operator: str
    duration_s: float
    num_clients: int = 0
    local_steps: int = 0
    # Actual total (client, step) pairs executed; overrides the
    # num_clients * local_steps estimate when heterogeneous compute profiles
    # give clients differing step counts.
    total_client_steps: int = 0
    extra: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def device_rounds_per_sec(self) -> float:
        return self.num_clients / self.duration_s if self.duration_s > 0 else 0.0

    @property
    def per_client_step_latency_s(self) -> float:
        """Amortized wall time per (client, local step) — the per-device-step
        cost the reference models as alpha=3.5 s/device-round on CPU actors
        (``utils_runner.py:941``)."""
        steps = self.total_client_steps or self.num_clients * max(self.local_steps, 1)
        return self.duration_s / steps if steps else 0.0


def _mean_step_latency(rows: List["RoundTiming"]) -> float:
    """Mean over client-advancing rows only: eval/custom rows (num_clients=0)
    contribute no steps and must not dilute the metric of record."""
    train_rows = [t for t in rows if t.num_clients > 0]
    if not train_rows:
        return 0.0
    return sum(t.per_client_step_latency_s for t in train_rows) / len(train_rows)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear interpolation between closest ranks (numpy's default): the
    nearest-rank rounding this replaces biased p95 on small samples — 10
    rounds' p95 answered the p100 (max) value."""
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class PerformanceManager:
    """Records timings, answers performance queries, controls the profiler."""

    def __init__(self, repo: Optional[TableRepo] = None, keep_last: int = 4096,
                 resilience_log=None, registry=None, tracer=None):
        # No repo by default: queries are answered from the bounded in-memory
        # window. Pass a repo to persist every row for external analysis —
        # retention is then the caller's policy (rows are append-only).
        # ``resilience_log`` — the ResilienceLog whose counters get_resilience
        # reports; pass the runner's instance when it is not the process-
        # global default (ResilienceConfig(log=...)).
        # ``registry`` / ``tracer`` — telemetry sinks this manager fronts
        # (None resolves the process defaults): every recorded timing also
        # feeds the live metrics registry, and stop_trace flushes the
        # tracer's runner spans next to the XLA trace. get_performance
        # answers stay computed from the recorded RoundTiming rows
        # themselves — the façade adds lenses, it never changes the numbers.
        self.repo = repo
        self.keep_last = keep_last
        self.resilience_log = resilience_log
        self.registry = registry
        self.tracer = tracer
        self._lock = threading.RLock()
        self._timings: Dict[str, List[RoundTiming]] = {}
        # task_id -> monotonic time of the last repo rehydration scan: a
        # monitoring loop polling an unknown task must not pay a full-table
        # scan per poll, but rows another process appends later (shared
        # sqlite repo) must still become visible — so misses retry after
        # ``rehydrate_ttl_s`` instead of being cached forever.
        self.rehydrate_ttl_s = 30.0
        self._rehydrate_scans: Dict[str, float] = {}
        self._trace_dir: Optional[str] = None
        self._trace_span_mark: float = 0.0

    # ------------------------------------------------------------- recording
    def record_round(self, timing: RoundTiming) -> None:
        from olearning_sim_tpu.telemetry import instrument

        instrument(
            "ols_engine_round_duration_seconds", self.registry
        ).labels(task_id=timing.task_id, operator=timing.operator).observe(
            timing.duration_s
        )
        with self._lock:
            rows = self._timings.setdefault(timing.task_id, [])
            rows.append(timing)
            if len(rows) > self.keep_last:
                del rows[: len(rows) - self.keep_last]
            if self.repo is None:
                return
            self.repo.add_item({
                "task_id": [timing.task_id],
                "round_idx": [str(timing.round_idx)],
                "operator": [timing.operator],
                "duration_s": [repr(timing.duration_s)],
                "num_clients": [str(timing.num_clients)],
                "local_steps": [str(timing.local_steps)],
                # total_client_steps rides in the extra JSON (no schema change)
                # so heterogeneous-profile per-client step latency stays
                # recomputable from a persisted repo, not just in memory.
                "extra": [json.dumps(
                    {**timing.extra,
                     "total_client_steps": timing.total_client_steps}
                )],
            })

    class _Timer:
        def __init__(self, mgr: "PerformanceManager", task_id: str,
                     round_idx: int, operator: str, num_clients: int,
                     local_steps: int, total_client_steps: int):
            self._mgr = mgr
            self._args = (task_id, round_idx, operator, num_clients,
                          local_steps, total_client_steps)
            # Values the caller learns mid-round (straggler/drop counts)
            # land in the recorded RoundTiming's extra via note().
            self.extra: Dict[str, float] = {}

        def note(self, **extra: float) -> None:
            """Attach extra key/values to the timing recorded at exit
            (called inside the ``with`` block)."""
            self.extra.update(extra)

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                task_id, round_idx, operator, nc, ls, tcs = self._args
                self._mgr.record_round(RoundTiming(
                    task_id=task_id, round_idx=round_idx, operator=operator,
                    duration_s=time.perf_counter() - self._t0,
                    num_clients=nc, local_steps=ls, total_client_steps=tcs,
                    extra=dict(self.extra),
                ))
            return False

    def time_round(self, task_id: str, round_idx: int, operator: str,
                   num_clients: int = 0, local_steps: int = 0,
                   total_client_steps: int = 0) -> "_Timer":
        """``with perf.time_round(...):`` around one operator execution."""
        return PerformanceManager._Timer(
            self, task_id, round_idx, operator, num_clients, local_steps,
            total_client_steps,
        )

    # --------------------------------------------------------------- queries
    def get_resilience(self, task_id: str) -> Dict[str, int]:
        """Resilience counters for one task (retries, rollbacks, quarantines,
        injected faults — olearning_sim_tpu.resilience.events). Part of the
        performance answer so robustness regressions ride the same query as
        throughput regressions."""
        log = self.resilience_log
        if log is None:
            from olearning_sim_tpu.resilience.events import global_log

            log = global_log()
        return log.counters(task_id)

    def _rehydrate(self, task_id: str) -> List[RoundTiming]:
        """Rebuild a task's RoundTiming window from the persisted repo (a
        restarted manager constructed over the same TableRepo must answer
        for completed tasks, not ``rounds_recorded: 0``). Unparseable rows
        are skipped — one corrupt row must not hide the rest."""
        if self.repo is None:
            return []
        # Scan fully under the lock: a concurrent get_performance for the
        # same task must wait and see the restored window, not race past a
        # pre-stamped TTL and answer rounds_recorded: 0 mid-scan.
        with self._lock:
            rows = self._timings.get(task_id)
            if rows:
                return list(rows)
            now = time.monotonic()
            last = self._rehydrate_scans.get(task_id)
            if last is not None and now - last < self.rehydrate_ttl_s:
                return []
            if len(self._rehydrate_scans) > 4096:
                # Bound the stamp map: keep the freshest half (a monitoring
                # loop cycling through many dead ids must not grow it
                # forever).
                for tid, _ in sorted(self._rehydrate_scans.items(),
                                     key=lambda kv: kv[1])[:2048]:
                    del self._rehydrate_scans[tid]
            restored: List[RoundTiming] = []
            for row in self.repo.query_all():
                if row.get("task_id") != task_id:
                    continue
                try:
                    extra = json.loads(row.get("extra") or "{}")
                    restored.append(RoundTiming(
                        task_id=task_id,
                        round_idx=int(row.get("round_idx") or 0),
                        operator=row.get("operator") or "",
                        duration_s=float(row.get("duration_s") or 0.0),
                        num_clients=int(row.get("num_clients") or 0),
                        local_steps=int(row.get("local_steps") or 0),
                        total_client_steps=int(
                            extra.pop("total_client_steps", 0) or 0
                        ),
                        extra={k: v for k, v in extra.items()},
                    ))
                except (TypeError, ValueError):
                    continue
            self._rehydrate_scans[task_id] = time.monotonic()
            if restored:
                window = self._timings.setdefault(task_id, [])
                window.extend(restored[-self.keep_last:])
                restored = list(window)
            return restored

    def get_performance(self, task_id: str) -> Dict[str, Any]:
        """Summary for one task: throughput + latency distribution
        (the ``PerformanceMgr.getPerformance`` answer)."""
        resilience = self.get_resilience(task_id)
        with self._lock:
            rows = list(self._timings.get(task_id, []))
        if not rows:
            rows = self._rehydrate(task_id)
        if not rows:
            return {"task_id": task_id, "rounds_recorded": 0,
                    "resilience": resilience}
        # Convergence-tracker eval rows feed ONLY the convergence block:
        # they are synthetic observability rows, and counting them in the
        # throughput aggregates would make the same workload report
        # different round_time_s / rounds_per_sec with tracking on vs
        # off (breaking comparability with every banked number).
        timing_rows = [t for t in rows if t.operator != "convergence_eval"]
        if not timing_rows:
            timing_rows = rows
        durations = sorted(t.duration_s for t in timing_rows)
        total_time = sum(durations)
        total_clients = sum(t.num_clients for t in timing_rows)
        distinct_rounds = len({t.round_idx for t in timing_rows})

        def _convergence() -> Optional[Dict[str, Any]]:
            # Quality series from the runner's convergence_eval timing
            # rows (one per tracker eval point; extras carry the
            # accuracy/clock scalars). Dedup by round, last row wins —
            # a rolled-back round's replay re-records its eval point.
            latest: Dict[int, RoundTiming] = {}
            for t in rows:
                if t.operator == "convergence_eval":
                    latest[t.round_idx] = t
            if not latest:
                return None
            series = [
                {"round": r, "acc": t.extra.get("eval_acc"),
                 "loss": t.extra.get("eval_loss"),
                 "sim_s": t.extra.get("sim_s"),
                 "wall_s": t.extra.get("wall_s")}
                for r, t in sorted(latest.items())
            ]
            newest = latest[max(latest)]
            accs = [p["acc"] for p in series if p["acc"] is not None]
            out: Dict[str, Any] = {
                "evals": len(series),
                "final_accuracy": accs[-1] if accs else None,
                "best_accuracy": max(accs) if accs else None,
                "reached": bool(newest.extra.get("reached")),
                "series": series,
            }
            for src, dst in (("target", "target_accuracy"),
                             ("rounds_to_target", "rounds_to_target"),
                             ("sim_s_to_target", "sim_seconds_to_target"),
                             ("wall_s_to_target", "wall_seconds_to_target")):
                if src in newest.extra:
                    out[dst] = newest.extra[src]
            return out

        def _extra_total(key: str) -> int:
            # Dedup by (round, operator), last row wins: a rolled-back round
            # that replays records a second timing row for the same round,
            # and summing both would double-count its stragglers/drops.
            latest: Dict[Any, RoundTiming] = {}
            for t in timing_rows:
                latest[(t.round_idx, t.operator)] = t
            return sum(int(t.extra.get(key, 0) or 0)
                       for t in latest.values())

        return {
            "task_id": task_id,
            "rounds_recorded": distinct_rounds,
            "operator_executions": len(timing_rows),
            "total_time_s": total_time,
            "rounds_per_sec": distinct_rounds / total_time if total_time else 0.0,
            "device_rounds_per_sec": total_clients / total_time if total_time else 0.0,
            "round_time_s": {
                "mean": total_time / len(durations),
                "p50": _percentile(durations, 0.50),
                "p95": _percentile(durations, 0.95),
                "max": durations[-1],
            },
            "per_client_step_latency_s": _mean_step_latency(timing_rows),
            # Deadline-aware rounds: clients that missed the round deadline
            # (stragglers) reported distinctly from trace-level drops.
            "stragglers_total": _extra_total("stragglers"),
            "dropped_total": _extra_total("dropped"),
            # Adversarial-client defense: in-jit clip count, anomaly flags,
            # and injected-attack totals (docs/resilience.md).
            "defense": {
                "clipped_total": _extra_total("clipped"),
                "flagged_total": _extra_total("flagged"),
                "attacked_total": _extra_total("attacked"),
            },
            # Time-to-accuracy: the convergence tracker's quality series
            # and to-target facts (None when tracking is off for the
            # task) — docs/performance.md "Time-to-accuracy benching".
            "convergence": _convergence(),
            "resilience": resilience,
        }

    def list_tasks(self) -> List[str]:
        with self._lock:
            return sorted(self._timings)

    # --------------------------------------------------------------- metrics
    def render_metrics(self, fmt: str = "prometheus") -> str:
        """The live metrics registry rendered for transport: Prometheus
        text exposition (default) or a JSON snapshot — the body of the
        PerformanceMgr ``getMetrics`` RPC."""
        from olearning_sim_tpu.telemetry import render_prometheus, snapshot

        if fmt in ("json", "snapshot"):
            return json.dumps(snapshot(self.registry))
        return render_prometheus(self.registry)

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Dict form of the registry (bench.py artifacts)."""
        from olearning_sim_tpu.telemetry import snapshot

        return snapshot(self.registry)

    # -------------------------------------------------------------- profiler
    def start_trace(self, logdir: str) -> bool:
        """Begin a ``jax.profiler`` trace (XLA op-level timeline viewable in
        TensorBoard/Perfetto). One trace at a time. A start that raises
        (unwritable logdir, half-initialized profiler session) leaves this
        manager armed for the next attempt instead of wedged "in a trace"
        forever."""
        import jax

        from olearning_sim_tpu.telemetry import default_tracer

        with self._lock:
            if self._trace_dir is not None:
                return False
            tracer = self.tracer if self.tracer is not None else \
                default_tracer()
            # Spans before this watermark belong to earlier rounds/traces
            # and have no counterpart in the XLA capture starting now.
            self._trace_span_mark = tracer.now()
            try:
                jax.profiler.start_trace(logdir)
            except BaseException:
                # jax may have partially opened a profiler session before
                # failing; close it so the retry doesn't hit "already
                # started".
                self._trace_dir = None
                with contextlib.suppress(Exception):
                    jax.profiler.stop_trace()
                raise
            self._trace_dir = logdir
            return True

    RUNNER_SPAN_FILE = "runner_spans.trace.json"

    def stop_trace(self) -> Optional[str]:
        import jax

        with self._lock:
            if self._trace_dir is None:
                return None
            jax.profiler.stop_trace()
            out, self._trace_dir = self._trace_dir, None
        # Flush the runner-level spans as Perfetto trace_event JSON next to
        # the XLA trace, so one directory opens both timelines. Best-effort:
        # span export must never turn a successful XLA capture into an error.
        from olearning_sim_tpu.telemetry import default_tracer

        tracer = self.tracer if self.tracer is not None else default_tracer()
        with contextlib.suppress(Exception):
            tracer.export(os.path.join(out, self.RUNNER_SPAN_FILE),
                          since_s=self._trace_span_mark)
        return out

"""Performance manager: round timing, throughput metrics, profiler traces.

The reference declares a ``PerformanceMgr`` gRPC service
(``ols_core/proto/performanceService.proto:4-6``) whose implementation
(``ols.performanceMgr.performance_manager``) was never released
(SURVEY.md section 2.6); the only in-repo performance data are MySQL lifecycle
timestamps. This module re-specifies it TPU-first: per-(round, operator) host
timings, FL throughput (rounds/sec, device-rounds/sec), per-client local-step
latency — the BASELINE.md metrics of record — plus ``jax.profiler`` trace
capture for XLA-level analysis.
"""

from olearning_sim_tpu.performancemgr.performance_manager import (
    PerformanceManager,
    RoundTiming,
)

__all__ = ["PerformanceManager", "RoundTiming"]

"""Gradient-fragment consumption (server-side receipt of client updates).

The reference pulls *fragments* — per-client model updates plus training
metrics — off a Pulsar topic via ``JsonFragmentRepo``/``ProtoFragmentRepo``
(``ofl_commons/infrastructure/FragmentRepo/json_fragment_repo.py:8-43``,
``proto_fragment_repo.py:5-38``); the base ``Fragment`` model was never
released (SURVEY.md section 2.6), so it is re-specified here from the fields
visible in the demos (``metrics.train_tp_fragment`` et al.).

In the rebuild the fast path never leaves the device (aggregation is an XLA
collective), so fragments are the *escape-hatch* transport: external operators
and cross-process deployments publish fragments onto a queue, and the
aggregator-side consumer drains them. ``QueueFragmentRepo`` is the in-process
transport; the deviceflow ``InboundRoom`` satisfies the same producer contract.
"""

from __future__ import annotations

import dataclasses
import json
import queue
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Fragment:
    """One client's update: identity, payload, and training metrics."""

    task_id: str
    client_id: str
    round_idx: int
    payload: Any = None  # model delta / weights, serialized by the producer
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)

    def serialize(self) -> str:
        return json.dumps({
            "task_id": self.task_id,
            "client_id": self.client_id,
            "round_idx": self.round_idx,
            "payload": self.payload,
            "metrics": self.metrics,
        })

    @classmethod
    def deserialize(cls, data: str) -> "Fragment":
        obj = json.loads(data)
        return cls(
            task_id=obj["task_id"],
            client_id=obj["client_id"],
            round_idx=int(obj["round_idx"]),
            payload=obj.get("payload"),
            metrics={k: float(v) for k, v in obj.get("metrics", {}).items()},
        )


class FragmentRepo:
    """Consumer interface: blocking pull of the next fragment."""

    def put_fragment(self, fragment: Fragment) -> None:
        raise NotImplementedError

    def get_fragment(self, timeout: Optional[float] = None) -> Optional[Fragment]:
        raise NotImplementedError

    def drain(self, max_items: int = 0) -> List[Fragment]:
        """Non-blocking drain of everything currently queued."""
        out: List[Fragment] = []
        while max_items <= 0 or len(out) < max_items:
            frag = self.get_fragment(timeout=0)
            if frag is None:
                break
            out.append(frag)
        return out


class QueueFragmentRepo(FragmentRepo):
    """In-process queue transport (the single-host Pulsar replacement)."""

    def __init__(self, maxsize: int = 0):
        self._q: "queue.Queue[Fragment]" = queue.Queue(maxsize=maxsize)

    def put_fragment(self, fragment: Fragment) -> None:
        self._q.put(fragment)

    def get_fragment(self, timeout: Optional[float] = None) -> Optional[Fragment]:
        try:
            if timeout == 0:
                return self._q.get_nowait()
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


class ResilientFragmentRepo(FragmentRepo):
    """Retry/backoff + fault injection around any fragment transport.

    A cross-process transport (Pulsar-alike, gRPC stream) drops and times out;
    this wrapper gives the aggregator-side consumer the same retry discipline
    as file I/O. Fault-injection points: ``fragment.put``, ``fragment.get``.
    """

    def __init__(self, inner: FragmentRepo, retry_policy=None, log=None,
                 task_id: str = ""):
        from olearning_sim_tpu.resilience import NO_RETRY

        self.inner = inner
        self.retry_policy = retry_policy if retry_policy is not None else NO_RETRY
        self.log = log
        self.task_id = task_id

    def put_fragment(self, fragment: Fragment) -> None:
        from olearning_sim_tpu.resilience import faults

        def op():
            faults.inject("fragment.put", context=fragment.client_id,
                          task_id=self.task_id)
            self.inner.put_fragment(fragment)

        self.retry_policy.call(op, point="fragment.put",
                               task_id=self.task_id, log=self.log)

    def get_fragment(self, timeout: Optional[float] = None) -> Optional[Fragment]:
        from olearning_sim_tpu.resilience import faults

        def op():
            faults.inject("fragment.get", task_id=self.task_id)
            return self.inner.get_fragment(timeout=timeout)

        return self.retry_policy.call(op, point="fragment.get",
                                      task_id=self.task_id, log=self.log)


class JsonFragmentRepo(QueueFragmentRepo):
    """JSON-wire variant (reference ``json_fragment_repo.py:8-43``): producers
    enqueue serialized strings, the consumer parses on receipt."""

    def put_serialized(self, data: str) -> None:
        self.put_fragment(Fragment.deserialize(data))

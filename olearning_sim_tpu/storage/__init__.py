"""Storage repos: file transfer backends + gradient-fragment consumption.

Re-specifies the reference's ``ols_core/ofl_commons/infrastructure/`` package,
whose base classes (``FileRepo``, ``FragmentRepo``/``Fragment``) are absent
from the open-source snapshot (SURVEY.md section 2.6) — only the S3/MinIO
concrete impls survive (``s3_file_repo.py:7-64``, ``minio_file_repo.py:22-65``).
"""

from olearning_sim_tpu.storage.file_repo import (
    FileRepo,
    FileTransferType,
    HttpFileRepo,
    LocalFileRepo,
    MinioFileRepo,
    ResilientFileRepo,
    S3FileRepo,
    fetch_operator_code,
    make_file_repo,
)
from olearning_sim_tpu.storage.fragment_repo import (
    Fragment,
    FragmentRepo,
    JsonFragmentRepo,
    QueueFragmentRepo,
    ResilientFragmentRepo,
)

__all__ = [
    "FileRepo",
    "FileTransferType",
    "LocalFileRepo",
    "HttpFileRepo",
    "S3FileRepo",
    "MinioFileRepo",
    "make_file_repo",
    "fetch_operator_code",
    "Fragment",
    "FragmentRepo",
    "JsonFragmentRepo",
    "QueueFragmentRepo",
    "ResilientFileRepo",
    "ResilientFragmentRepo",
]

"""File-transfer backends behind one interface.

The reference moves datasets, models, and operator code through four transfer
types (``FileTransferType`` enum, ``ols_core/proto/taskService.proto:131-136``:
FILE/HTTP/S3/MINIO), with concrete repos at
``ofl_commons/infrastructure/FileRepo/s3_file_repo.py:7-64`` (boto3) and
``minio_file_repo.py:22-65`` (minio), a wget/urllib path for HTTP
(``taskMgr/utils/utils_run_task.py:174-325``), and plain paths for FILE.
The abstract base the reference imports (``file_repo.py``) was never released,
so this module re-specifies it: upload / download / delete / list /
download_payload (download-then-delete, the reference's payload semantics).

S3 and MinIO impls import their SDKs lazily and raise a clear error when the
SDK is not installed — single-host mode needs neither.
"""

from __future__ import annotations

import abc
import enum
import os
import shutil
import tempfile
import urllib.request
import zipfile
from typing import List, Optional

from olearning_sim_tpu.proto import taskservice_pb2 as _pb

# Single source of truth is the wire enum (taskservice.proto FileTransferType:
# FILE/HTTP/S3/MINIO); this IntEnum view adds Python enum ergonomics without
# duplicating the values.
FileTransferType = enum.IntEnum(
    "FileTransferType", dict(_pb.FileTransferType.items())
)


class FileRepo(abc.ABC):
    """Narrow file-store interface shared by all transfer backends."""

    @abc.abstractmethod
    def upload_file(self, local_path: str, remote_path: str) -> bool:
        """Copy a local file into the store at ``remote_path``."""

    @abc.abstractmethod
    def download_file(self, remote_path: str, local_path: str) -> bool:
        """Copy ``remote_path`` out of the store to a local file."""

    @abc.abstractmethod
    def delete_file(self, remote_path: str) -> bool:
        """Remove ``remote_path`` from the store."""

    @abc.abstractmethod
    def list_files(self, prefix: str = "") -> List[str]:
        """All stored paths starting with ``prefix``."""

    def download_payload(self, remote_path: str, local_path: str) -> bool:
        """Download then delete (reference ``s3_file_repo.py`` download_payload
        semantics: payloads are consumed, not mirrored)."""
        if not self.download_file(remote_path, local_path):
            return False
        return self.delete_file(remote_path)

    def exists(self, remote_path: str) -> bool:
        return remote_path in self.list_files(remote_path)


class LocalFileRepo(FileRepo):
    """FILE transfer type: a rooted directory tree.

    Remote paths are interpreted relative to ``root``; absolute remote paths
    are allowed and used as-is (the reference's FILE mode passes raw host
    paths, ``utils_run_task.py:196-214``).
    """

    def __init__(self, root: str = "/"):
        self.root = root

    def _resolve(self, remote_path: str) -> str:
        if os.path.isabs(remote_path):
            return remote_path
        return os.path.join(self.root, remote_path)

    def upload_file(self, local_path: str, remote_path: str) -> bool:
        try:
            dest = self._resolve(remote_path)
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            # Stage-then-rename: a concurrent reader of ``dest`` must never
            # see a half-copied file (os.replace is atomic within one fs);
            # unique staging name so two uploaders don't clobber each other.
            # The staged data is fsynced before the rename and the parent
            # directory after it — without both, a host crash can replay the
            # rename but not the data and "commit" a zero-length/torn file.
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(dest) + ".", dir=os.path.dirname(dest) or "."
            )
            os.close(fd)
            from olearning_sim_tpu.utils.durable import (
                commit_replace,
                copy_file_durable,
            )

            copy_file_durable(local_path, tmp)
            commit_replace(tmp, dest)
            return True
        except OSError:
            return False

    def exists(self, remote_path: str) -> bool:
        # Direct stat — the base-class list_files() walk would scan the whole
        # root tree (root may be "/") just to answer a membership question.
        return os.path.isfile(self._resolve(remote_path))

    def download_file(self, remote_path: str, local_path: str) -> bool:
        try:
            src = self._resolve(remote_path)
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            shutil.copyfile(src, local_path)
            return True
        except OSError:
            return False

    def delete_file(self, remote_path: str) -> bool:
        try:
            os.remove(self._resolve(remote_path))
            return True
        except OSError:
            return False

    def list_files(self, prefix: str = "") -> List[str]:
        base = self._resolve(prefix)
        found: List[str] = []
        if os.path.isfile(base):
            return [prefix]
        search_root = base if os.path.isdir(base) else (os.path.dirname(base) or ".")
        if os.path.abspath(search_root) == os.path.sep:
            # A filesystem-rooted walk from "/" (root="/" with an empty or
            # one-level prefix) would scan the entire host. Demand intent.
            raise ValueError(
                "LocalFileRepo.list_files would walk the whole filesystem "
                f"(root={self.root!r}, prefix={prefix!r}); construct the repo "
                "with an explicit root directory instead"
            )
        if not os.path.isdir(search_root):
            return []
        for dirpath, _dirs, files in os.walk(search_root):
            for f in files:
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, self.root) if not os.path.isabs(prefix) else full
                if rel.startswith(prefix):
                    found.append(rel)
        return sorted(found)


class HttpFileRepo(FileRepo):
    """HTTP transfer type: download-only (the reference fetches HTTP data with
    wget/urllib, ``utils_run_task.py:216-233``; it never uploads over HTTP)."""

    def upload_file(self, local_path: str, remote_path: str) -> bool:
        raise NotImplementedError("HTTP transfer is download-only")

    def download_file(self, remote_path: str, local_path: str) -> bool:
        try:
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            with urllib.request.urlopen(remote_path) as resp, open(local_path, "wb") as out:
                shutil.copyfileobj(resp, out)
            return True
        except Exception:
            # http.client errors (IncompleteRead etc.) are not OSErrors; keep
            # the bool contract and don't leave a truncated file behind.
            try:
                os.remove(local_path)
            except OSError:
                pass
            return False

    def delete_file(self, remote_path: str) -> bool:
        raise NotImplementedError("HTTP transfer is download-only")

    def list_files(self, prefix: str = "") -> List[str]:
        raise NotImplementedError("HTTP transfer is download-only")


class S3FileRepo(FileRepo):
    """S3 transfer type (reference ``s3_file_repo.py:7-64``, boto3). The SDK is
    imported lazily so single-host deployments need no boto3."""

    def __init__(self, endpoint_url: str, access_key: str, secret_key: str, bucket: str):
        try:
            import boto3  # noqa: PLC0415
        except ImportError as e:  # pragma: no cover - exercised only without boto3
            raise RuntimeError("S3 transfer requires boto3 (not installed)") from e
        self.bucket = bucket
        self._client = boto3.client(
            "s3",
            endpoint_url=endpoint_url,
            aws_access_key_id=access_key,
            aws_secret_access_key=secret_key,
        )

    def upload_file(self, local_path: str, remote_path: str) -> bool:
        try:
            self._client.upload_file(local_path, self.bucket, remote_path)
            return True
        except Exception:
            return False

    def download_file(self, remote_path: str, local_path: str) -> bool:
        try:
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            self._client.download_file(self.bucket, remote_path, local_path)
            return True
        except Exception:
            return False

    def delete_file(self, remote_path: str) -> bool:
        try:
            self._client.delete_object(Bucket=self.bucket, Key=remote_path)
            return True
        except Exception:
            return False

    def list_files(self, prefix: str = "") -> List[str]:
        out: List[str] = []
        paginator = self._client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            out.extend(obj["Key"] for obj in page.get("Contents", []))
        return out


class MinioFileRepo(FileRepo):
    """MINIO transfer type (reference ``minio_file_repo.py:22-65``)."""

    def __init__(self, endpoint: str, access_key: str, secret_key: str, bucket: str,
                 secure: bool = False):
        try:
            from minio import Minio  # noqa: PLC0415
        except ImportError as e:  # pragma: no cover - exercised only without minio
            raise RuntimeError("MinIO transfer requires the minio SDK (not installed)") from e
        self.bucket = bucket
        self._client = Minio(endpoint, access_key=access_key, secret_key=secret_key,
                             secure=secure)

    def upload_file(self, local_path: str, remote_path: str) -> bool:
        try:
            self._client.fput_object(self.bucket, remote_path, local_path)
            return True
        except Exception:
            return False

    def download_file(self, remote_path: str, local_path: str) -> bool:
        try:
            self._client.fget_object(self.bucket, remote_path, local_path)
            return True
        except Exception:
            return False

    def delete_file(self, remote_path: str) -> bool:
        try:
            self._client.remove_object(self.bucket, remote_path)
            return True
        except Exception:
            return False

    def list_files(self, prefix: str = "") -> List[str]:
        try:
            return [o.object_name
                    for o in self._client.list_objects(self.bucket, prefix=prefix,
                                                       recursive=True)]
        except Exception:
            return []


class ResilientFileRepo(FileRepo):
    """Wrap any :class:`FileRepo` with retry/backoff + fault injection.

    The bool-contract methods (upload/download/delete) are retried both on
    raised exceptions and on returned ``False`` (the backends' native failure
    signal); after the policy is exhausted the last result/exception is
    surfaced unchanged, so callers keep their existing contracts.
    ``NotImplementedError`` (capability statements, e.g. HTTP upload) passes
    straight through. Fault-injection points: ``storage.upload``,
    ``storage.download``, ``storage.delete``, ``storage.list``.
    """

    def __init__(self, inner: FileRepo, retry_policy=None, log=None,
                 task_id: str = ""):
        from olearning_sim_tpu.resilience import NO_RETRY

        self.inner = inner
        self.retry_policy = retry_policy if retry_policy is not None else NO_RETRY
        self.log = log
        self.task_id = task_id

    def _call(self, point: str, context: str, fn, *args,
              bool_contract: bool = True):
        from olearning_sim_tpu.resilience import faults

        def op():
            spec = faults.fire(point, context=context, task_id=self.task_id)
            if spec is not None:
                if bool_contract and spec.error in ("false", "corrupt"):
                    return False
                # Non-bool APIs (list_files) get the exception flavor even
                # for "false" specs — returning False would violate their
                # List[str] contract.
                raise faults.exception_for(spec, point, context)
            return fn(*args)

        return self.retry_policy.call(
            op, retry_if=(lambda r: r is False) if bool_contract else None,
            point=point, task_id=self.task_id, log=self.log,
        )

    def upload_file(self, local_path: str, remote_path: str) -> bool:
        return self._call("storage.upload", remote_path,
                          self.inner.upload_file, local_path, remote_path)

    def download_file(self, remote_path: str, local_path: str) -> bool:
        return self._call("storage.download", remote_path,
                          self.inner.download_file, remote_path, local_path)

    def delete_file(self, remote_path: str) -> bool:
        return self._call("storage.delete", remote_path,
                          self.inner.delete_file, remote_path)

    def list_files(self, prefix: str = "") -> List[str]:
        return self._call("storage.list", prefix, self.inner.list_files,
                          prefix, bool_contract=False)

    def exists(self, remote_path: str) -> bool:
        # Delegate so LocalFileRepo's direct-stat fast path survives wrapping.
        return self.inner.exists(remote_path)


def storage_settings_from_env() -> dict:
    """Object-store connection settings from the environment (the reference
    reads them from ``config/manager_config.yaml``; the deployment config
    system maps that file onto these variables)."""
    return {
        "endpoint": os.environ.get("OLS_STORAGE_ENDPOINT", ""),
        "access_key": os.environ.get("OLS_STORAGE_ACCESS_KEY", ""),
        "secret_key": os.environ.get("OLS_STORAGE_SECRET_KEY", ""),
        "bucket": os.environ.get("OLS_STORAGE_BUCKET", ""),
        "secure": os.environ.get("OLS_STORAGE_SECURE", "") == "1",
    }


def make_file_repo(transfer_type: FileTransferType, *, root: str = "/",
                   endpoint: str = "", access_key: str = "", secret_key: str = "",
                   bucket: str = "", secure: bool = False,
                   retry_policy=None) -> FileRepo:
    """Factory keyed by the proto transfer-type enum (the dispatch the
    reference does ad hoc at every download site, ``utils_run_task.py:174-325``).

    ``retry_policy`` — optional :class:`~olearning_sim_tpu.resilience.RetryPolicy`;
    when given the repo is wrapped in :class:`ResilientFileRepo` (transient
    I/O failures retried with backoff, fault-injection points armed)."""

    def _wrap(repo: FileRepo) -> FileRepo:
        if retry_policy is None:
            return repo
        return ResilientFileRepo(repo, retry_policy=retry_policy)

    t = FileTransferType(transfer_type)
    if t == FileTransferType.FILE:
        return _wrap(LocalFileRepo(root=root))
    if t == FileTransferType.HTTP:
        return _wrap(HttpFileRepo())
    if t in (FileTransferType.S3, FileTransferType.MINIO) and not endpoint:
        env = storage_settings_from_env()
        if not env["endpoint"]:
            raise ValueError(
                f"{t.name} transfer type needs object-store settings; pass "
                "endpoint/keys/bucket or set OLS_STORAGE_ENDPOINT / "
                "OLS_STORAGE_ACCESS_KEY / OLS_STORAGE_SECRET_KEY / "
                "OLS_STORAGE_BUCKET"
            )
        endpoint = env["endpoint"]
        access_key = access_key or env["access_key"]
        secret_key = secret_key or env["secret_key"]
        bucket = bucket or env["bucket"]
        secure = secure or env["secure"]
    if t == FileTransferType.S3:
        return _wrap(S3FileRepo(endpoint_url=endpoint, access_key=access_key,
                                secret_key=secret_key, bucket=bucket))
    return _wrap(MinioFileRepo(endpoint=endpoint, access_key=access_key,
                               secret_key=secret_key, bucket=bucket,
                               secure=secure))


def fetch_operator_code(repo: FileRepo, remote_path: str, dest_dir: str,
                        unzip: Optional[bool] = None) -> str:
    """Fetch user operator code (zip or single file) into ``dest_dir`` and
    return the code directory — the reference's ``get_operator_code``
    (``taskMgr/utils/utils_runner.py:684-782``) without the temp-dir juggling.
    Zips are extracted; a plain file is copied as-is."""
    os.makedirs(dest_dir, exist_ok=True)
    name = os.path.basename(remote_path)
    local = os.path.join(dest_dir, name)
    if not repo.download_file(remote_path, local):
        raise FileNotFoundError(f"operator code not found: {remote_path}")
    is_zip = unzip if unzip is not None else name.endswith(".zip")
    if is_zip:
        with zipfile.ZipFile(local) as zf:
            zf.extractall(dest_dir)
        os.remove(local)
    return dest_dir

"""Parsers for the benchmark datasets' canonical on-disk formats.

The reference downloads a per-task archive and feeds CSV/MNN files to
operator subprocesses (``ols_core/taskMgr/utils/utils_run_task.py:174-325``);
the expected file names per task type live in
``ols_core/config/task_type_config.yaml``. The rebuild ingests the standard
public formats of the BASELINE datasets directly:

- MNIST / FEMNIST-style: IDX (``train-images-idx3-ubyte`` etc., the
  yann.lecun.com binary layout; FEMNIST additionally carries a writer-id
  array or LEAF JSON).
- CIFAR-10 / CIFAR-100: the "binary version" (``data_batch_*.bin`` /
  ``train.bin``: 1 or 2 label bytes + 3072 image bytes per record).
- Sent140: CSV with (polarity, ..., user, text) columns, hashed-token
  encoding.
- NPZ: ``{"x": ..., "y": ..., ["writer": ...]}`` escape hatch for
  pre-processed populations.

All parsers return ``(x, y, writer)`` where ``x`` is float32 in [0, 1]
(images) or int32 token ids (text), ``y`` is int32 labels, and ``writer``
is an optional int32 natural-partition key (FEMNIST writers, Sent140
users).
"""

from __future__ import annotations

import csv
import gzip
import io
import json
import os
import pickle
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

Parsed = Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]


def _open_maybe_gzip(path: str):
    with open(path, "rb") as f:
        magic = f.read(2)
    if magic == b"\x1f\x8b":
        return gzip.open(path, "rb")
    return open(path, "rb")


def read_idx(path: str) -> np.ndarray:
    """Read one IDX file (optionally gzipped). Layout: 2 zero bytes, dtype
    code, ndim, then ndim big-endian uint32 dims, then row-major data."""
    with _open_maybe_gzip(path) as f:
        raw = f.read()
    if len(raw) < 4:
        raise ValueError(f"{path}: truncated IDX header")
    zeros, dtype_code, ndim = raw[0] << 8 | raw[1], raw[2], raw[3]
    if zeros != 0:
        raise ValueError(f"{path}: bad IDX magic {raw[:4]!r}")
    dtypes = {
        0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
        0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"), 0x0E: np.dtype(">f8"),
    }
    if dtype_code not in dtypes:
        raise ValueError(f"{path}: unknown IDX dtype 0x{dtype_code:02x}")
    dims = struct.unpack(f">{ndim}I", raw[4 : 4 + 4 * ndim])
    data = np.frombuffer(raw, dtypes[dtype_code], offset=4 + 4 * ndim)
    expected = int(np.prod(dims)) if dims else 0
    if data.size < expected:
        raise ValueError(f"{path}: IDX payload shorter than header dims {dims}")
    return data[:expected].reshape(dims)


def load_mnist_dir(d: str, split: str = "train") -> Parsed:
    """MNIST from a directory of IDX files. ``split``: train | test (t10k)."""
    stems = {"train": ["train"], "test": ["t10k", "test"]}[split]
    img = _find_file(d, [f"{s}-images" for s in stems], ["idx3-ubyte", "idx3-ubyte.gz"])
    lab = _find_file(d, [f"{s}-labels" for s in stems], ["idx1-ubyte", "idx1-ubyte.gz"])
    x = read_idx(img).astype(np.float32) / 255.0
    y = read_idx(lab).astype(np.int32)
    if x.ndim == 3:
        x = x[..., None]  # [N, 28, 28, 1]
    writer = None
    wfile = _find_file(d, [f"{s}-writers" for s in stems], ["idx1-ubyte", "npy"], required=False)
    if wfile:  # FEMNIST-style writer partition key
        writer = (np.load(wfile) if wfile.endswith(".npy") else read_idx(wfile)).astype(np.int32)
    return x, y, writer


def load_cifar_dir(d: str, split: str = "train", coarse: bool = False) -> Parsed:
    """CIFAR-10/100 "binary version". CIFAR-10: 1 label byte + 3072 image
    bytes; CIFAR-100: coarse + fine label bytes + 3072. Detects the variant
    from the file names (``data_batch_*.bin``/``test_batch.bin`` vs
    ``train.bin``/``test.bin``)."""
    names = sorted(os.listdir(d))
    c10 = [n for n in names if n.startswith("data_batch") and n.endswith(".bin")]
    c100_train = [n for n in names if n == "train.bin"]
    if split == "train":
        files, label_bytes = (c10, 1) if c10 else (c100_train, 2)
    else:
        files = [n for n in names if n in ("test_batch.bin", "test.bin")]
        label_bytes = 1 if c10 or any(n == "test_batch.bin" for n in files) else 2
        if any(n == "test.bin" for n in files) and not c10:
            label_bytes = 2
    if not files:
        raise FileNotFoundError(f"no CIFAR binary files for split={split!r} in {d}")
    rec = label_bytes + 3072
    xs, ys = [], []
    for n in files:
        raw = np.fromfile(os.path.join(d, n), np.uint8)
        if raw.size % rec != 0:
            raise ValueError(f"{n}: size {raw.size} not a multiple of record {rec}")
        rows = raw.reshape(-1, rec)
        # CIFAR-100 rows: [coarse, fine, pixels]; fine is the standard label.
        ys.append(rows[:, 0 if (label_bytes == 1 or coarse) else 1])
        xs.append(rows[:, label_bytes:])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y = np.concatenate(ys).astype(np.int32)
    return x.astype(np.float32) / 255.0, y, None


class _CifarUnpickler(pickle.Unpickler):
    """Unpickler allowing only what the published CIFAR batches contain:
    plain containers (handled without ``find_class``) and numpy array
    reconstruction. Everything else — ``os.system``, ``builtins.eval``,
    arbitrary class instantiation — raises instead of importing."""

    _ALLOWED = {
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "_reconstruct"),  # numpy >= 2 module name
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        # protocol-2 pickles route py2-str/bytes payloads through
        # _codecs.encode (side-effect-free byte encoding) — the genuine
        # python-2 CIFAR batches need it under encoding="bytes".
        ("_codecs", "encode"),
    }

    def find_class(self, module, name):  # noqa: D102 — see class docstring
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"global {module}.{name} forbidden in CIFAR batch pickles"
        )


def load_cifar_python_dir(d: str, split: str = "train", coarse: bool = False) -> Parsed:
    """CIFAR-10/100 "python version" — the format of the actually-published
    ``cifar-10-python.tar.gz`` / ``cifar-100-python.tar.gz`` archives: pickled
    dicts with ``data`` (uint8 [N, 3072], channel-planar R/G/B row-major) and
    ``labels`` / ``fine_labels``+``coarse_labels``. File names:
    ``data_batch_1..5``/``test_batch`` (CIFAR-10) or ``train``/``test``
    (CIFAR-100). Keys may be bytes (the published files are python-2
    pickles). Unpickling is RESTRICTED: the published batches need nothing
    beyond dict/list/bytes plus numpy array reconstruction, so
    :class:`_CifarUnpickler` refuses every other global — a malicious
    pickle arriving through the remote FileRepo download path gets
    ``UnpicklingError``, not code execution (the reference trusts its
    downloaded task data outright, ``utils_run_task.py:174-325``)."""
    names = sorted(os.listdir(d))
    if any(n.startswith("data_batch") for n in names):
        files = ([n for n in names if n.startswith("data_batch")]
                 if split == "train" else ["test_batch"])
        label_key = "labels"
    else:
        files = ["train"] if split == "train" else ["test"]
        label_key = "coarse_labels" if coarse else "fine_labels"
    missing = [n for n in files if n not in names]
    if missing:
        raise FileNotFoundError(f"CIFAR python files {missing} not in {d}")

    def get(blob, key):
        return blob[key.encode()] if key.encode() in blob else blob[key]

    xs, ys = [], []
    for n in files:
        with open(os.path.join(d, n), "rb") as f:
            blob = _CifarUnpickler(f, encoding="bytes").load()
        xs.append(np.asarray(get(blob, "data"), np.uint8))
        ys.append(np.asarray(get(blob, label_key), np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return x.astype(np.float32) / 255.0, np.concatenate(ys), None


def hash_tokenize(text: str, vocab_size: int, seq_len: int) -> np.ndarray:
    """Deterministic hashed-token encoding (token 0 = padding). Stands in
    for the DistilBERT tokenizer without bundling vocab files; stable across
    processes (crc32, not PYTHONHASHSEED)."""
    import zlib

    toks = [1 + zlib.crc32(w.lower().encode()) % (vocab_size - 1)
            for w in text.split()[:seq_len]]
    out = np.zeros(seq_len, np.int32)
    out[: len(toks)] = toks
    return out


def load_sent140_csv(path: str, vocab_size: int = 30522, seq_len: int = 64,
                     max_rows: Optional[int] = None) -> Parsed:
    """Sent140 CSV: ``polarity,id,date,query,user,text``; polarity 0/4 ->
    label 0/1; ``user`` is the natural partition key."""
    xs, ys, users = [], [], []
    user_ids: Dict[str, int] = {}
    with open(path, newline="", encoding="utf-8", errors="replace") as f:
        for i, row in enumerate(csv.reader(f)):
            if max_rows is not None and i >= max_rows:
                break
            if len(row) < 6:
                continue
            polarity, user, text = row[0], row[4], row[5]
            try:
                label = {0: 0, 4: 1, 2: 1}[int(polarity)]
            except (ValueError, KeyError):
                continue
            xs.append(hash_tokenize(text, vocab_size, seq_len))
            ys.append(label)
            users.append(user_ids.setdefault(user, len(user_ids)))
    if not xs:
        raise ValueError(f"{path}: no parsable sent140 rows")
    return (np.stack(xs), np.asarray(ys, np.int32), np.asarray(users, np.int32))


def load_leaf_json(path: str, vocab_size: int = 30522, seq_len: int = 64) -> Parsed:
    """LEAF-format JSON (FEMNIST/Sent140 as published by the LEAF benchmark):
    ``{"users": [...], "user_data": {u: {"x": [...], "y": [...]}}}``."""
    with open(path, encoding="utf-8") as f:
        blob = json.load(f)
    xs: List[np.ndarray] = []
    ys: List[int] = []
    writers: List[int] = []
    for wid, user in enumerate(blob["users"]):
        ud = blob["user_data"][user]
        for xv, yv in zip(ud["x"], ud["y"]):
            if isinstance(xv, str):
                xs.append(hash_tokenize(xv, vocab_size, seq_len))
            else:
                a = np.asarray(xv, np.float32)
                if a.size == 784:  # FEMNIST flattened 28x28
                    a = a.reshape(28, 28, 1)
                xs.append(a)
            ys.append(int(yv))
            writers.append(wid)
    return np.stack(xs), np.asarray(ys, np.int32), np.asarray(writers, np.int32)


def load_npz(path: str) -> Parsed:
    blob = np.load(path, allow_pickle=False)
    if "x" not in blob or "y" not in blob:
        raise KeyError(f"{path}: npz must contain 'x' and 'y'")
    x = blob["x"]
    if np.issubdtype(x.dtype, np.floating):
        x = x.astype(np.float32)
    writer = blob["writer"].astype(np.int32) if "writer" in blob else None
    return x, blob["y"].astype(np.int32), writer


def _find_file(d: str, stems: List[str], suffixes: List[str], required: bool = True) -> Optional[str]:
    names = os.listdir(d)
    for stem in stems:
        for suf in suffixes:
            for n in names:
                if n.startswith(stem) and n.endswith(suf):
                    return os.path.join(d, n)
    if required:
        raise FileNotFoundError(f"no file matching {stems}x{suffixes} in {d} (have {sorted(names)[:10]})")
    return None


def detect_and_load(d: str, split: str = "train", **text_kwargs) -> Parsed:
    """Sniff the dataset format inside directory ``d`` and parse it.

    Detection order: NPZ ({split}.npz or data.npz) -> IDX (MNIST/FEMNIST) ->
    CIFAR binaries -> LEAF JSON -> Sent140 CSV.
    """
    names = sorted(os.listdir(d))
    for cand in (f"{split}.npz", "data.npz"):
        if cand in names:
            return load_npz(os.path.join(d, cand))
    if any("idx3-ubyte" in n for n in names):
        return load_mnist_dir(d, split)
    if any(n.endswith(".bin") for n in names):
        return load_cifar_dir(d, split)
    if any(n.startswith("data_batch") for n in names) or (
        "meta" in names and {"train", "test"} & set(names)
    ):
        return load_cifar_python_dir(d, split)
    ljson = [n for n in names if n.endswith(".json")]
    if ljson:
        tk = {k: v for k, v in text_kwargs.items() if k in ("vocab_size", "seq_len")}
        return load_leaf_json(os.path.join(d, ljson[0]), **tk)
    csvs = [n for n in names if n.endswith(".csv")]
    if csvs:
        pick = [n for n in csvs if split in n] or csvs
        return load_sent140_csv(os.path.join(d, pick[0]), **text_kwargs)
    # single subdirectory (zip roots often nest once)
    subdirs = [n for n in names if os.path.isdir(os.path.join(d, n))]
    if len(subdirs) == 1:
        return detect_and_load(os.path.join(d, subdirs[0]), split, **text_kwargs)
    raise FileNotFoundError(f"unrecognized dataset layout in {d}: {names[:10]}")

"""Client partitioners: real labels -> federated populations.

The reference stages pre-partitioned per-client archives (its
``HybridDataSplitter`` re-splits them with sklearn ``train_test_split``,
``ols_core/taskMgr/utils/utils_runner.py:195-382``); the rebuild partitions
centrally-loaded arrays into the engine's rectangular ``ClientDataset``:

- ``dirichlet``: label-skew non-IID (Dirichlet(alpha) over classes per
  client — the BASELINE configs' non-IID recipe).
- ``iid``: uniform shuffle-split.
- ``by_writer``: natural partition (FEMNIST writers, Sent140 users).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from olearning_sim_tpu.engine.client_data import ClientDataset


def iid_assignments(n: int, num_clients: int, rng: np.random.Generator) -> List[np.ndarray]:
    idx = rng.permutation(n)
    return [np.sort(part) for part in np.array_split(idx, num_clients)]


def dirichlet_assignments(
    y: np.ndarray, num_clients: int, alpha: float, rng: np.random.Generator
) -> List[np.ndarray]:
    """Non-IID label-skew split: each client draws class proportions from
    Dirichlet(alpha); samples of each class are dealt to clients according
    to the normalized per-class column of the proportion matrix. Every
    sample is assigned exactly once (deal-without-replacement, unlike
    naive per-client sampling which duplicates/drops rows)."""
    y = np.asarray(y)
    classes = np.unique(y)
    props = rng.dirichlet([alpha] * len(classes), size=num_clients)  # [C, K]
    out: List[List[np.ndarray]] = [[] for _ in range(num_clients)]
    for k, cls in enumerate(classes):
        rows = rng.permutation(np.flatnonzero(y == cls))
        col = props[:, k]
        if col.sum() <= 0:
            col = np.full(num_clients, 1.0 / num_clients)
        cuts = (np.cumsum(col / col.sum()) * len(rows)).astype(int)[:-1]
        for ci, part in enumerate(np.split(rows, cuts)):
            out[ci].append(part)
    return [np.sort(np.concatenate(parts)) if parts else np.empty(0, int) for parts in out]


def writer_assignments(
    writer: np.ndarray, num_clients: int, rng: np.random.Generator
) -> List[np.ndarray]:
    """Natural partition: one client per writer/user. If there are more
    writers than requested clients, writers are grouped round-robin; if
    fewer, the surplus clients get empty shards (weight 0 downstream)."""
    writer = np.asarray(writer)
    wids = rng.permutation(np.unique(writer))
    out: List[List[np.ndarray]] = [[] for _ in range(num_clients)]
    for i, w in enumerate(wids):
        out[i % num_clients].append(np.flatnonzero(writer == w))
    return [np.sort(np.concatenate(p)) if p else np.empty(0, int) for p in out]


def to_client_dataset(
    x: np.ndarray,
    y: np.ndarray,
    assignments: Sequence[np.ndarray],
    n_local: int,
    rng: Optional[np.random.Generator] = None,
    min_samples: int = 1,
) -> ClientDataset:
    """Pack per-client index lists into the engine's rectangular arrays.

    Clients with more than ``n_local`` samples are subsampled (without
    replacement); clients with fewer keep what they have (``num_samples``
    marks the valid prefix; padding rows are zeros and carry no weight
    because minibatch indices are drawn in ``[0, num_samples)``). Clients
    under ``min_samples`` get weight 0 (never sampled, never aggregated) —
    the deviceflow trace compiler treats them like churned-out devices.
    """
    rng = rng or np.random.default_rng(0)
    C = len(assignments)
    xs = np.zeros((C, n_local) + x.shape[1:], x.dtype)
    ys = np.zeros((C, n_local), np.int32)
    ns = np.zeros(C, np.int32)
    for ci, idx in enumerate(assignments):
        idx = np.asarray(idx)
        if len(idx) > n_local:
            idx = rng.choice(idx, size=n_local, replace=False)
        ns[ci] = len(idx)
        if len(idx):
            xs[ci, : len(idx)] = x[idx]
            ys[ci, : len(idx)] = y[idx]
    weight = np.where(ns >= min_samples, ns, 0).astype(np.float32)
    return ClientDataset(
        x=xs,
        y=ys,
        num_samples=np.maximum(ns, 1),
        client_uid=np.arange(C, dtype=np.int32),
        weight=weight,
        num_real_clients=C,
    )


def partition(
    x: np.ndarray,
    y: np.ndarray,
    num_clients: int,
    n_local: int,
    scheme: str = "dirichlet",
    alpha: float = 0.5,
    writer: Optional[np.ndarray] = None,
    seed: int = 0,
) -> ClientDataset:
    """One-call partitioner used by the task bridge."""
    rng = np.random.default_rng(seed)
    if scheme == "by_writer":
        if writer is None:
            raise ValueError("scheme='by_writer' needs a writer array (FEMNIST/Sent140 formats provide one)")
        asg = writer_assignments(writer, num_clients, rng)
    elif scheme == "dirichlet":
        asg = dirichlet_assignments(y, num_clients, alpha, rng)
    elif scheme == "iid":
        asg = iid_assignments(len(y), num_clients, rng)
    else:
        raise ValueError(f"unknown partition scheme {scheme!r}")
    return to_client_dataset(x, y, asg, n_local, rng)

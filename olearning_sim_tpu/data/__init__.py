"""Real-dataset ingestion (MNIST / CIFAR / FEMNIST / Sent140 / NPZ):
format parsers, federated partitioners, and FileRepo-backed fetching."""

from olearning_sim_tpu.data.formats import (
    detect_and_load,
    hash_tokenize,
    load_cifar_dir,
    load_leaf_json,
    load_mnist_dir,
    load_npz,
    load_sent140_csv,
    read_idx,
)
from olearning_sim_tpu.data.ingest import (
    clear_cache,
    fetch_dataset_dir,
    load_arrays,
    load_population,
)
from olearning_sim_tpu.data.partition import (
    dirichlet_assignments,
    iid_assignments,
    partition,
    to_client_dataset,
    writer_assignments,
)

__all__ = [
    "detect_and_load", "hash_tokenize", "load_cifar_dir", "load_leaf_json",
    "load_mnist_dir", "load_npz", "load_sent140_csv", "read_idx",
    "clear_cache", "fetch_dataset_dir", "load_arrays", "load_population",
    "dirichlet_assignments", "iid_assignments", "partition",
    "to_client_dataset", "writer_assignments",
]

"""Task-data ingestion: ``dataPath`` + ``dataTransferType`` -> placed population.

Reference behavior being matched (``ols_core/taskMgr/utils/utils_run_task.py:
174-325`` ``download_data_files``): each actor downloads the task's archive
via FILE/HTTP/S3/MINIO, unzips it, and feeds per-phone files to operator
subprocesses. Here ingestion happens once per task: fetch archive -> parse
the standard dataset format (:mod:`formats`) -> partition into the
rectangular client population (:mod:`partition`). The fetched/parsed arrays
are cached per (path, split) so multi-operator tasks don't re-download.
"""

from __future__ import annotations

import collections
import os
import tempfile
import threading
import zipfile
from typing import Any, Optional, Tuple

import numpy as np

from olearning_sim_tpu.data import formats
from olearning_sim_tpu.data.partition import partition

# LRU-bounded: a long-lived manager running many tasks must not retain
# every task's parsed arrays for process lifetime. The cap is datasets,
# not bytes — typical entries are one benchmark archive each.
_CACHE_MAX = max(1, int(os.environ.get("OLS_INGEST_CACHE_MAX", "4")))
_cache: "collections.OrderedDict[Tuple[str, str], Any]" = collections.OrderedDict()
_cache_lock = threading.Lock()


def fetch_dataset_dir(
    data_path: str,
    transfer_type: Any = None,
    storage_settings: Optional[dict] = None,
) -> str:
    """Materialize ``data_path`` as a local directory.

    - local directory -> itself
    - local/remote ``.zip`` or ``.tar[.gz]`` -> fetched (FileRepo for
      non-FILE transfer types), extracted into a temp dir (path-traversal
      guarded), nested-once roots flattened by
      :func:`formats.detect_and_load`. Tarballs matter because the genuine
      published archives (``cifar-10-python.tar.gz`` etc.) are tars, not
      zips — they ingest unchanged.
    """
    import tarfile

    if os.path.isdir(data_path):
        return data_path
    local_arc = data_path
    is_remote = transfer_type is not None and getattr(transfer_type, "name", str(transfer_type)) not in ("FILE", "0")
    if is_remote or not os.path.exists(data_path):
        from olearning_sim_tpu.storage import FileTransferType, make_file_repo

        tt = transfer_type if transfer_type is not None else FileTransferType.FILE
        repo = make_file_repo(FileTransferType(int(tt)) if isinstance(tt, int) else tt,
                              **(storage_settings or {}))
        local_arc = os.path.join(tempfile.mkdtemp(prefix="olsdata_"), os.path.basename(data_path))
        if not repo.download_file(data_path, local_arc):
            raise FileNotFoundError(f"could not fetch dataset {data_path!r} via {tt}")
    if zipfile.is_zipfile(local_arc):
        out = tempfile.mkdtemp(prefix="olsdata_x_")
        with zipfile.ZipFile(local_arc) as zf:
            for m in zf.namelist():
                target = os.path.realpath(os.path.join(out, m))
                if not target.startswith(os.path.realpath(out) + os.sep):
                    raise ValueError(f"zip entry escapes extraction root: {m!r}")
            zf.extractall(out)
        return out
    if tarfile.is_tarfile(local_arc):
        out = tempfile.mkdtemp(prefix="olsdata_x_")
        with tarfile.open(local_arc) as tf:
            try:
                # filter="data" (py>=3.12) rejects absolute paths, ..
                # traversal, links outside the root, and device/sticky bits.
                tf.extractall(out, filter="data")
            except TypeError:
                # Older interpreters: the zip branch's traversal guard, by
                # hand — plus a link-member rejection the zip branch does
                # not need (zipfile never materializes symlinks, tarfile
                # does: a symlink pointing outside the root followed by a
                # member extracting *through* it would pass a name-only
                # realpath check, because the realpath runs before the
                # symlink exists on disk).
                root = os.path.realpath(out)
                for m in tf.getmembers():
                    if m.issym() or m.islnk():
                        raise ValueError(
                            f"tar link member rejected: {m.name!r} -> "
                            f"{m.linkname!r} (published dataset archives "
                            f"contain no links)"
                        )
                    target = os.path.realpath(os.path.join(out, m.name))
                    if not target.startswith(root + os.sep):
                        raise ValueError(
                            f"tar entry escapes extraction root: {m.name!r}"
                        )
                tf.extractall(out)
        return out
    raise ValueError(
        f"dataset path {data_path!r} is neither a directory, a zip, nor a tar"
    )


def load_arrays(
    data_path: str,
    split: str = "train",
    transfer_type: Any = None,
    storage_settings: Optional[dict] = None,
    **text_kwargs,
) -> formats.Parsed:
    """Fetch + parse with per-(path, split) caching."""
    key = (data_path, split)
    with _cache_lock:
        if key in _cache:
            _cache.move_to_end(key)
            return _cache[key]
    d = fetch_dataset_dir(data_path, transfer_type, storage_settings)
    parsed = formats.detect_and_load(d, split, **text_kwargs)
    with _cache_lock:
        _cache[key] = parsed
        _cache.move_to_end(key)
        while len(_cache) > _CACHE_MAX:
            _cache.popitem(last=False)
    return parsed


def clear_cache() -> None:
    with _cache_lock:
        _cache.clear()


def load_population(
    data_path: str,
    num_clients: int,
    n_local: int,
    scheme: str = "dirichlet",
    alpha: float = 0.5,
    seed: int = 0,
    transfer_type: Any = None,
    storage_settings: Optional[dict] = None,
    eval_split: str = "test",
    eval_n: Optional[int] = None,
    **text_kwargs,
):
    """Full ingestion: returns ``(ClientDataset, (eval_x, eval_y) | None,
    num_classes)``. The eval set comes from the archive's test split when
    present, else a held-out tail of train (deterministic, disjoint from
    every client shard by construction: holdout rows are removed before
    partitioning)."""
    x, y, writer = load_arrays(
        data_path, "train", transfer_type, storage_settings, **text_kwargs
    )
    eval_data = None
    try:
        ex, ey, _ = load_arrays(
            data_path, eval_split, transfer_type, storage_settings, **text_kwargs
        )
        eval_data = (ex, ey)
    except (FileNotFoundError, KeyError):
        if eval_n:
            hold = min(int(eval_n), len(y) // 5)
            rng = np.random.default_rng([seed, 0xE7A1])
            hold_idx = rng.choice(len(y), size=hold, replace=False)
            mask = np.ones(len(y), bool)
            mask[hold_idx] = False
            eval_data = (x[hold_idx], y[hold_idx])
            x, y = x[mask], y[mask]
            if writer is not None:
                writer = writer[mask]
    if eval_data is not None and eval_n:
        eval_data = (eval_data[0][: int(eval_n)], eval_data[1][: int(eval_n)])
    ds = partition(
        x, y, num_clients, n_local,
        scheme=scheme, alpha=alpha, writer=writer, seed=seed,
    )
    num_classes = int(np.max(y)) + 1 if len(y) else 0
    return ds, eval_data, num_classes

"""Hybrid data splitting: give the logical and device halves disjoint shards.

Reference: ``HybridDataSplitter.split_data_classification``
(``ols_core/taskMgr/utils/utils_runner.py:195-382``) — after the ILP decides
how many device-rounds run logically vs on phones, download the dataset,
stratified-split it by label in that proportion, re-zip the device share and
re-upload both halves. The rebuild does the same through the
:mod:`formats`/:mod:`ingest` parsers, staging each half as an NPZ zip next
to the original archive (``<base>_logical.zip`` / ``<base>_device.zip``).
"""

from __future__ import annotations

import os
import tempfile
import zipfile
from typing import Any, Optional, Tuple

import numpy as np

from olearning_sim_tpu.data import ingest


def stratified_split_indices(
    y: np.ndarray, device_fraction: float, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-label proportional split (the reference's
    ``train_test_split(..., stratify=y)``): every label contributes
    ``device_fraction`` of its rows to the device half. Returns
    (logical_idx, device_idx) — disjoint, covering all rows."""
    if not 0.0 <= device_fraction <= 1.0:
        raise ValueError(f"device_fraction must be in [0,1], got {device_fraction}")
    rng = np.random.default_rng(seed)
    logical, device = [], []
    for label in np.unique(y):
        rows = rng.permutation(np.flatnonzero(y == label))
        k = int(round(len(rows) * device_fraction))
        device.append(rows[:k])
        logical.append(rows[k:])
    return np.sort(np.concatenate(logical)), np.sort(np.concatenate(device))


def _write_npz_zip(path: str, x: np.ndarray, y: np.ndarray,
                   writer: Optional[np.ndarray]) -> None:
    with tempfile.TemporaryDirectory() as d:
        npz = os.path.join(d, "train.npz")
        payload = {"x": x, "y": y}
        if writer is not None:
            payload["writer"] = writer
        np.savez_compressed(npz, **payload)
        with zipfile.ZipFile(path, "w") as zf:
            zf.write(npz, "train.npz")


def stage_hybrid_split(
    data_path: str,
    device_fraction: float,
    transfer_type: Any = None,
    storage_settings: Optional[dict] = None,
    seed: int = 0,
    repo=None,
    dest_prefix: Optional[str] = None,
) -> Tuple[str, str]:
    """Fetch ``data_path``, split it, stage both halves, return
    ``(logical_path, device_path)``.

    With a ``repo`` (FileRepo), the halves are uploaded next to the
    original (``<base>_logical.zip``/``<base>_device.zip``) — the
    reference's re-zip-and-re-upload step. Without one, they are staged
    as local files (single-host mode), under ``dest_prefix`` when given.
    """
    x, y, writer = ingest.load_arrays(
        data_path, "train", transfer_type, storage_settings
    )
    li, di = stratified_split_indices(y, device_fraction, seed)
    base = data_path[:-4] if data_path.endswith(".zip") else data_path
    if dest_prefix is None:
        dest_prefix = os.path.join(
            tempfile.mkdtemp(prefix="olshybrid_"), os.path.basename(base)
        )
    local_logical = f"{dest_prefix}_logical.zip"
    local_device = f"{dest_prefix}_device.zip"
    _write_npz_zip(local_logical, x[li], y[li],
                   writer[li] if writer is not None else None)
    _write_npz_zip(local_device, x[di], y[di],
                   writer[di] if writer is not None else None)
    if repo is None:
        return local_logical, local_device
    remote_logical = f"{base}_logical.zip"
    remote_device = f"{base}_device.zip"
    if not repo.upload_file(local_logical, remote_logical):
        raise IOError(f"failed to upload logical share to {remote_logical}")
    if not repo.upload_file(local_device, remote_device):
        raise IOError(f"failed to upload device share to {remote_device}")
    return remote_logical, remote_device


def device_fraction_of(td) -> float:
    """Device share of the total simulated device-rounds for one TargetData
    (post-allocation): sum(device) / (sum(logical) + sum(device))."""
    logical = sum(td.allocation.allocationLogicalSimulation)
    device = sum(td.allocation.allocationDeviceSimulation)
    total = logical + device
    return device / total if total else 0.0

"""Tensor-parallel coverage lint: an ``mp > 1`` config must actually shard.

The per-leaf indivisibility fallback in ``parallel/tp.tp_param_specs`` is
silent by design — a head count that doesn't divide ``mp`` replicates that
leaf and the program stays correct. But a CHECKED-IN task config asking
for ``{"parallel": {"mp": N}}`` on a model whose tensors mostly can't
shard is a configuration bug: every chip holds (almost) the full model,
the mp axis burns devices for no memory or FLOP win, and nothing fails at
runtime (``warn_if_unsharded`` warns below 1%, which a CI log swallows).

This analyzer makes the threshold a repo invariant: for every JSON task
config under ``configs/`` whose engine params request ``mp > 1``, the
model's parameter shapes are abstractly evaluated (``jax.eval_shape`` —
no weights, no device work) and the spec coverage from
``tp_param_specs`` must shard at least :data:`MIN_SHARDED_FRACTION` of
the parameter ELEMENTS; a violation names the unmatched (replicated)
leaves so the fix — pick divisible head/FFN counts, or drop the mp
request — is one read away. The same number every runtime build publishes
as the ``ols_engine_tp_sharded_ratio`` gauge (build_fedcore), measured
statically at lint time.

Registered in ``scripts/check_all.py`` as ``tp_coverage``; standalone::

    python -m olearning_sim_tpu.analysis.tp_coverage
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# An mp>1 request must distribute at least half of the parameter volume;
# below that the dominant memory term is replicated and the axis is
# (mostly) decorative. docs/performance.md documents the knob.
MIN_SHARDED_FRACTION = 0.5

CONFIGS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "configs",
)


def _engine_param_blocks(cfg: Dict) -> List[Dict]:
    """Every operator's parsed engine-params dict in one task config."""
    blocks = []
    for op in (cfg.get("operatorflow") or {}).get("operators", []):
        sim = op.get("logical_simulation") or {}
        raw = sim.get("operator_params")
        if not raw:
            continue
        try:
            params = json.loads(raw) if isinstance(raw, str) else raw
        except json.JSONDecodeError:
            continue  # malformed params are the submit validator's finding
        if isinstance(params, dict):
            blocks.append(params)
    return blocks


def measure_config(params: Dict) -> Optional[Tuple[float, List[str], int]]:
    """(sharded_fraction, replicated leaf names, mp) for one engine-params
    block, or None when the block doesn't request tensor parallelism."""
    from olearning_sim_tpu.parallel.mesh import ParallelConfig

    par = params.get("parallel")
    if not par:
        return None
    parallel = ParallelConfig.from_dict(par)
    if parallel.mp <= 1:
        return None
    import jax
    from jax.sharding import PartitionSpec as P

    from olearning_sim_tpu.models import get_model
    from olearning_sim_tpu.parallel.tp import sharded_fraction, tp_param_specs

    model_cfg = params.get("model", {})
    # Same default as task_bridge's build path: a name-less model block is
    # a VALID config (mlp2), not an unmeasurable one.
    spec = get_model(model_cfg.get("name", "mlp2"))
    model = spec.build(**(model_cfg.get("overrides") or {}))
    in_shape = tuple(model_cfg.get("input_shape") or spec.example_input_shape)

    def init(rng):
        dummy = jax.numpy.zeros((1,) + in_shape, spec.input_dtype)
        return model.init(rng, dummy)["params"]

    shapes = jax.eval_shape(init, jax.random.key(0))
    specs = tp_param_specs(shapes, parallel.mp)
    frac = sharded_fraction(shapes, specs)
    flat_specs = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P)
    )[0]
    unsharded = [
        jax.tree_util.keystr(path)
        for path, s in flat_specs
        if not any(ax is not None for ax in s)
    ]
    return frac, unsharded, parallel.mp


def check(configs_dir: Optional[str] = None,
          min_fraction: float = MIN_SHARDED_FRACTION) -> List[str]:
    """Findings for every mp>1 config below the coverage threshold
    (empty = clean). ``configs_dir`` is injectable for tests."""
    root = configs_dir or CONFIGS_DIR
    problems: List[str] = []
    for path in sorted(glob.glob(os.path.join(root, "*.json"))):
        rel = os.path.basename(path)
        try:
            with open(path, encoding="utf-8") as f:
                cfg = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue  # unreadable/malformed configs are other lints' findings
        for params in _engine_param_blocks(cfg):
            try:
                measured = measure_config(params)
            except Exception as e:  # noqa: BLE001 — name the config, keep linting
                problems.append(
                    f"{rel}: mp coverage could not be measured ({e}) — a "
                    f"parallel block that cannot be abstractly evaluated "
                    f"will also fail at build time"
                )
                continue
            if measured is None:
                continue
            frac, unsharded, mp = measured
            if frac < min_fraction:
                preview = ", ".join(unsharded[:6])
                more = (f" (+{len(unsharded) - 6} more)"
                        if len(unsharded) > 6 else "")
                problems.append(
                    f"{rel}: parallel.mp={mp} shards only {frac:.1%} of "
                    f"parameter elements (threshold {min_fraction:.0%}) — "
                    f"the mp axis is mostly replication; unmatched leaves: "
                    f"{preview}{more}. Pick head/FFN counts divisible by "
                    f"{mp}, or drop the parallel block "
                    f"(docs/performance.md, 'Model parallelism')"
                )
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    problems = check()
    for p in problems:
        print(f"tp_coverage: {p}", file=sys.stderr)
    if problems:
        print(f"tp_coverage: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("tp_coverage: OK — every mp>1 config shards "
          f">={MIN_SHARDED_FRACTION:.0%} of parameter elements")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())

"""Convergence gate: model quality regressions fail CI like budget
regressions.

The HLO audit catches a program whose *memory/communication* shape
regressed; nothing caught a change that silently degrades *model
quality* — an aggressive staleness discount, a defense that stopped
binding under attack, a drift path training on the wrong labels. This
analyzer runs a small fixed-seed convergence grid (the
:func:`~olearning_sim_tpu.engine.convergence.run_convergence_task`
harness — the SAME code path ``bench.py --convergence`` banks, so the
gate and the bench can never measure different things) and diffs each
entry's deterministic record against the blessed envelopes in
``analysis/convergence.json``:

====================  ===================================================
entry                 engine config
====================  ===================================================
clean                 plain fedavg (the quality baseline)
async_staleness       buffered async commits, polynomial staleness
                      discount (PR 8) — prices the 2.19x throughput
                      headline in accuracy terms
attack_trimmed_mean   20% scale-factor-30 attackers + clip/trimmed-mean
                      defense (PR 5/6) — the defended entry must stay
                      near the clean baseline
attack_undefended     the same attack with NO defense — pins the
                      attack's measured damage (an attack that stops
                      biting is also a regression: the defended entry
                      would pass vacuously)
drift_trace           scenario label drift (PR 10), resident execution
====================  ===================================================

Compared fields (per-entry tolerance, ``tolerances`` in the envelope
file, overridable per entry): ``final_accuracy`` / ``best_accuracy`` /
``accuracy_at_round_budget`` within ± ``accuracy``; ``reached`` must
match; ``rounds_to_target`` within ± ``rounds_to_target``. Wall-clock
fields are never compared (measured, non-deterministic); simulated-time
fields are recorded unenforced, like the HLO audit's ``memory`` stats.

Re-bless after an INTENTIONAL quality change with
``python -m olearning_sim_tpu.analysis.convergence_gate --bless`` (or
``python scripts/check_all.py --bless-convergence``) and commit the
diff — docs/performance.md "Time-to-accuracy benching" documents the
workflow.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

ENVELOPES_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "convergence.json")

# |fresh - blessed| may not exceed these. Accuracy drift across jaxlib
# point releases on CPU is zero for fixed seeds in practice; the headroom
# absorbs cross-platform float reassociation without letting a real
# quality regression (attacks move accuracy by >0.1) through.
DEFAULT_TOLERANCES = {
    "accuracy": 0.05,
    "rounds_to_target": 2,
}

# One shared tiny family: learnable blob population, fixed seeds, a
# budget small enough that the whole grid stays under ~a minute on CPU.
GATE_BASE = dict(
    seed=3, num_clients=64, n_local=8, input_shape=(16,), num_classes=4,
    class_sep=2.0, eval_n=512, rounds=12, batch=4, local_steps=4,
    block_clients=16, hidden=(16,), local_lr=0.3,
)
GATE_CONVERGENCE = {
    "target_accuracy": 0.7,
    "eval_every": 1,
    "round_budget": 8,
}

# The attacked pair mirrors the PR 5 chaos acceptance shape: a scale
# attack big enough that the undefended run measurably degrades while
# clip + trimmed-mean holds the defended run near the clean baseline.
_ATTACK = {"mode": "scale", "factor": 30.0, "fraction": 0.2}

GATE_ENTRIES: Dict[str, Dict] = {
    "clean": {},
    "async_staleness": {
        "async_config": {"buffer_size": 16, "schedule": "polynomial",
                         "staleness_alpha": 0.5, "default_step_s": 0.05,
                         "jitter": 0.2},
    },
    "attack_trimmed_mean": {
        "attack": dict(_ATTACK),
        "defense": {"clip_norm": 5.0, "aggregator": "trimmed_mean",
                    "trim_fraction": 0.25},
    },
    "attack_undefended": {
        "attack": dict(_ATTACK),
    },
    "drift_trace": {
        "scenario": {"drift_period_rounds": 4, "round_seconds": 600.0},
    },
}

# Deterministic accuracy fields diffed against the envelope; simulated
# clocks are recorded unenforced (they move with pacing-config edits that
# are not quality regressions).
ACCURACY_FIELDS = ("final_accuracy", "best_accuracy",
                   "accuracy_at_round_budget")
RECORDED_FIELDS = ACCURACY_FIELDS + (
    "target_accuracy", "reached", "rounds_to_target",
    "sim_seconds_to_target", "sim_seconds_total",
    "device_rounds_committed", "accuracy_per_1k_device_rounds",
)


def run_entry(name: str, overrides: Optional[Dict] = None) -> Dict:
    """Run one gate entry end-to-end; returns its convergence record.
    ``overrides`` merges into the entry's engine-config kwargs (a test's
    planted regression: ``{"defense": None}``, an aggressive
    ``staleness_alpha``, ...)."""
    from olearning_sim_tpu.engine.convergence import run_convergence_task

    spec = dict(GATE_ENTRIES[name])
    for k, v in (overrides or {}).items():
        if v is None:
            spec.pop(k, None)
        elif isinstance(v, dict) and isinstance(spec.get(k), dict):
            spec[k] = {**spec[k], **v}
        else:
            spec[k] = v
    return run_convergence_task(
        name=name, convergence=dict(GATE_CONVERGENCE), **GATE_BASE, **spec
    )


def _envelope_entry(record: Dict) -> Dict:
    return {k: record.get(k) for k in RECORDED_FIELDS}


def compare(name: str, measured: Dict, golden: Dict,
            tolerances: Optional[Dict] = None) -> List[str]:
    """Findings for one entry: fresh record vs its blessed envelope."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    tol.update(golden.get("tolerances") or {})
    problems = []
    for field in ACCURACY_FIELDS:
        m, g = measured.get(field), golden.get(field)
        if m is None or g is None:
            if m != g:
                problems.append(
                    f"{name}: {field} is "
                    f"{'missing' if m is None else m} but the envelope "
                    f"says {g} — the eval series changed shape; re-bless "
                    f"if intentional"
                )
            continue
        if abs(float(m) - float(g)) > tol["accuracy"]:
            direction = "degraded" if m < g else "moved"
            problems.append(
                f"{name}: {field} {direction} to {float(m):.4f} (blessed "
                f"{float(g):.4f}, tolerance ±{tol['accuracy']}) — a "
                f"change shifted this entry's model quality; fix it or "
                f"re-bless with the diff justified"
            )
    if bool(measured.get("reached")) != bool(golden.get("reached")):
        problems.append(
            f"{name}: target {GATE_CONVERGENCE['target_accuracy']} "
            f"reached={bool(measured.get('reached'))} vs blessed "
            f"reached={bool(golden.get('reached'))} — the entry "
            f"{'no longer' if golden.get('reached') else 'suddenly'} "
            f"converges to target within the budget"
        )
    else:
        m_r, g_r = measured.get("rounds_to_target"), \
            golden.get("rounds_to_target")
        if m_r is not None and g_r is not None and \
                abs(int(m_r) - int(g_r)) > tol["rounds_to_target"]:
            problems.append(
                f"{name}: rounds_to_target moved to {m_r} (blessed {g_r}, "
                f"tolerance ±{tol['rounds_to_target']}) — time-to-accuracy "
                f"shifted; fix it or re-bless"
            )
    return problems


def load_envelopes(path: Optional[str] = None) -> Dict:
    with open(path or ENVELOPES_PATH, encoding="utf-8") as f:
        return json.load(f)


def check(only: Optional[List[str]] = None,
          overrides: Optional[Dict[str, Dict]] = None,
          envelopes: Optional[Dict] = None,
          envelopes_path: Optional[str] = None) -> List[str]:
    """Run the gate grid (or the ``only`` subset) and diff against the
    blessed envelopes; returns findings (empty = clean). ``overrides``
    plants per-entry engine-config changes (the seeded-regression tests
    prove the gate bites)."""
    if envelopes is None:
        try:
            envelopes = load_envelopes(envelopes_path)
        except OSError as e:
            return [
                f"cannot read blessed convergence envelopes ({e}); "
                f"generate with `python -m "
                f"olearning_sim_tpu.analysis.convergence_gate --bless`"
            ]
    entries = envelopes.get("entries", {})
    tolerances = envelopes.get("tolerances")
    names = list(GATE_ENTRIES) if only is None else list(only)
    unknown = [n for n in names if n not in GATE_ENTRIES]
    if unknown:
        raise ValueError(
            f"unknown convergence-gate entries {unknown} "
            f"(known: {sorted(GATE_ENTRIES)})"
        )
    problems: List[str] = []
    for name in names:
        golden = entries.get(name)
        if golden is None:
            problems.append(
                f"{name}: entry missing from convergence.json — bless the "
                f"grid (`python -m "
                f"olearning_sim_tpu.analysis.convergence_gate --bless`)"
            )
            continue
        record = run_entry(name, (overrides or {}).get(name))
        problems.extend(compare(name, record, golden, tolerances))
    if only is None:
        for stale in sorted(set(entries) - set(GATE_ENTRIES)):
            problems.append(
                f"{stale}: envelope entry no longer in the gate grid — "
                f"remove it (re-bless)"
            )
    return problems


def bless(path: Optional[str] = None) -> Dict:
    """Run the full grid and (re)write the blessed envelope file.
    Hand-added per-entry ``tolerances`` overrides in the existing file
    survive the re-bless (they are configuration, not measurement)."""
    out = path or ENVELOPES_PATH
    prior_tol: Dict[str, Dict] = {}
    try:
        for name, entry in load_envelopes(out).get("entries", {}).items():
            if entry.get("tolerances"):
                prior_tol[name] = entry["tolerances"]
    except (OSError, ValueError):
        pass
    envelopes = {
        "_comment": (
            "Blessed convergence envelopes per (family x engine-config) "
            "gate entry. Regenerate with `python -m "
            "olearning_sim_tpu.analysis.convergence_gate --bless` after "
            "an INTENTIONAL quality change and commit the diff "
            "(docs/performance.md, Time-to-accuracy benching)."
        ),
        "tolerances": dict(DEFAULT_TOLERANCES),
        "base": {**GATE_BASE, "input_shape": list(GATE_BASE["input_shape"]),
                 "hidden": list(GATE_BASE["hidden"]),
                 "convergence": dict(GATE_CONVERGENCE)},
        "entries": {
            name: {**_envelope_entry(run_entry(name)),
                   **({"tolerances": prior_tol[name]}
                      if name in prior_tol else {})}
            for name in GATE_ENTRIES
        },
    }
    with open(out, "w", encoding="utf-8") as f:
        json.dump(envelopes, f, indent=1, sort_keys=True)
        f.write("\n")
    return envelopes


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--bless" in argv:
        envelopes = bless()
        print(f"convergence_gate: blessed {len(envelopes['entries'])} "
              f"entries -> {ENVELOPES_PATH}")
        return 0
    only = None
    if "--only" in argv:
        only = argv[argv.index("--only") + 1].split(",")
    problems = check(only=only)
    for p in problems:
        print(f"convergence_gate: {p}", file=sys.stderr)
    if problems:
        print(f"convergence_gate: {len(problems)} violation(s)",
              file=sys.stderr)
        return 1
    print("convergence_gate: OK — quality within blessed envelopes")
    return 0


if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.exit(main())

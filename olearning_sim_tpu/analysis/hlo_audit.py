"""HLO budget audit: every round-program variant's compiled artifact must
match the checked-in golden budgets.

For each grid variant (analysis/grid), the audit measures from the REAL
compiled HLO (never the Python):

- **collectives** — dominant per-device output bytes per collective kind.
  A new kind appearing, a kind disappearing, or bytes growing past the
  tolerance fails: this is how the O(clients x params) all-gather class of
  regression (PR 6) is caught grid-wide, not just on the one defended
  program ``check_hlo_collectives`` pins.
- **largest_buffer_bytes** — the biggest single instruction result the
  program materializes. A silent return of a clients x params buffer (or
  an accidental full-matrix intermediate) shows up here.
- **dtypes** — the census of result element types. ``f64`` anywhere is a
  precision leak (default-f32 jax; a stray Python double crossed the jit
  boundary) and always fails; any other NEW dtype fails against golden.
- **donated_inputs / aliased_outputs** — ``donate_argnums`` donations in
  ``fedcore.py`` must survive lowering (``jax.buffer_donor`` /
  ``tf.aliasing_output`` arg attributes) AND compilation (the module
  header's ``input_output_alias`` table). A lost donation doubles peak
  param memory at scale and fails exactly.

Budgets live in ``analysis/budgets.json`` — regenerate with
``python scripts/check_all.py --bless`` (or ``python -m
olearning_sim_tpu.analysis.hlo_audit --bless``) after an INTENTIONAL
program change, and commit the diff; docs/static_analysis.md documents
the workflow. Tolerances are per-file ``tolerances`` ratios (and
per-variant overrides under a variant's ``"tolerances"`` key): measured
bytes may not exceed golden x ratio. ``memory`` stats are recorded for
operators but not enforced (CPU/TPU buffer assignment differs too much
across jaxlib versions to pin).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

from olearning_sim_tpu.engine import hlo_stats

BUDGETS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "budgets.json")

# Measured value may not exceed golden * ratio. Collective bytes are pure
# shape math (exact); the largest buffer can drift with XLA fusion
# decisions across versions, so it gets headroom.
DEFAULT_TOLERANCES = {
    "collective_bytes": 1.0,
    "largest_buffer_bytes": 1.25,
}


def measure(art: Dict) -> Dict:
    """The budgetable facts of one variant's artifacts (grid.artifacts)."""
    compiled = art["compiled"]
    lowered = art["lowered_a"]
    largest = hlo_stats.largest_result(compiled)
    return {
        "collectives": hlo_stats.dominant_collectives(compiled),
        "largest_buffer_bytes": largest["bytes"] if largest else 0,
        "largest_buffer_op": largest["op"] if largest else None,
        "dtypes": sorted(hlo_stats.dtype_census(compiled)),
        "donated_inputs": hlo_stats.count_donated_inputs(lowered),
        "aliased_outputs": len(
            hlo_stats.parse_input_output_aliases(compiled)
        ),
        "params_bytes": art["params_bytes"],
        "clients": art["clients"],
        "memory": art.get("memory"),
    }


def compare(name: str, measured: Dict, golden: Dict,
            tolerances: Optional[Dict] = None) -> List[str]:
    """Findings for one variant: measured vs its golden budget entry."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    tol.update(golden.get("tolerances") or {})
    problems = []

    if "f64" in measured["dtypes"] and not golden.get("allow_f64"):
        problems.append(
            f"{name}: f64 appears in the compiled program (dtype census "
            f"{measured['dtypes']}) — a Python double leaked across the "
            f"jit boundary (precision + 2x memory regression)"
        )
    new_dtypes = set(measured["dtypes"]) - set(golden.get("dtypes", []))
    new_dtypes.discard("f64")  # already reported above, more precisely
    if new_dtypes:
        problems.append(
            f"{name}: new dtypes {sorted(new_dtypes)} in the compiled "
            f"program (golden census: {golden.get('dtypes')}); re-bless "
            f"if intentional"
        )

    g_coll = golden.get("collectives", {})
    m_coll = measured["collectives"]
    for kind in sorted(set(m_coll) - set(g_coll)):
        problems.append(
            f"{name}: new collective kind {kind!r} "
            f"({m_coll[kind]} bytes/device) not in the golden budget — "
            f"the program's communication shape changed; re-bless if "
            f"intentional"
        )
    for kind in sorted(set(g_coll) - set(m_coll)):
        problems.append(
            f"{name}: collective {kind!r} disappeared from the compiled "
            f"program (golden: {g_coll[kind]} bytes/device) — a sharded "
            f"path silently vanishing also passes byte checks, so this "
            f"fails loudly"
        )
    ratio = tol["collective_bytes"]
    for kind in sorted(set(g_coll) & set(m_coll)):
        if m_coll[kind] > g_coll[kind] * ratio:
            problems.append(
                f"{name}: {kind} grew to {m_coll[kind]} bytes/device "
                f"(golden {g_coll[kind]}, tolerance x{ratio}) — collective "
                f"bytes are shape math, so this is a real layout change"
            )

    g_big = golden.get("largest_buffer_bytes", 0)
    if measured["largest_buffer_bytes"] > g_big * tol["largest_buffer_bytes"]:
        problems.append(
            f"{name}: largest live buffer grew to "
            f"{measured['largest_buffer_bytes']} bytes "
            f"({measured['largest_buffer_op']}; golden {g_big}, tolerance "
            f"x{tol['largest_buffer_bytes']}) — check for a rematerialized "
            f"clients x params intermediate"
        )

    for field, label in (("donated_inputs", "lowered donation markers"),
                         ("aliased_outputs",
                          "compiled input-output aliases")):
        if measured[field] != golden.get(field, 0):
            problems.append(
                f"{name}: {label} changed: {measured[field]} vs golden "
                f"{golden.get(field, 0)} — a lost donation doubles peak "
                f"param memory; a gained one should be blessed"
            )
    return problems


def load_budgets(path: Optional[str] = None) -> Dict:
    with open(path or BUDGETS_PATH, encoding="utf-8") as f:
        return json.load(f)


def static_hbm_oracle(path: Optional[str] = None) -> Dict[str, Dict]:
    """Static peak-memory facts per variant for the chip-pool scheduler's
    admission oracle (``taskmgr/pool.CostOracle``): the blessed compiled-HLO
    budgets reduced to ``{variant: {largest_buffer_bytes, params_bytes,
    clients}}``. This is a *static* memory oracle — measured from the real
    compiled program's buffer assignment, available before any execution,
    which is exactly what admission control needs to refuse a placement
    that would OOM a mesh instead of letting it crash."""
    budgets = load_budgets(path)
    return {
        name: {
            "largest_buffer_bytes": entry.get("largest_buffer_bytes", 0),
            "params_bytes": entry.get("params_bytes", 0),
            "clients": entry.get("clients", 1),
        }
        for name, entry in budgets.get("variants", {}).items()
    }


def check(artifacts_by_name: Optional[Dict[str, Dict]] = None,
          budgets: Optional[Dict] = None,
          budgets_path: Optional[str] = None) -> List[str]:
    """Audit the grid against budgets; returns findings (empty = clean)."""
    from olearning_sim_tpu.analysis import grid

    if budgets is None:
        try:
            budgets = load_budgets(budgets_path)
        except OSError as e:
            return [
                f"cannot read golden budgets ({e}); generate with "
                f"`python scripts/check_all.py --bless`"
            ]
    if artifacts_by_name is None:
        artifacts_by_name = grid.grid_artifacts()

    tolerances = budgets.get("tolerances")
    entries = budgets.get("variants", {})
    problems: List[str] = []
    for name, art in sorted(artifacts_by_name.items()):
        golden = entries.get(name)
        if golden is None:
            problems.append(
                f"{name}: variant missing from budgets.json — bless the "
                f"grid (`python scripts/check_all.py --bless`)"
            )
            continue
        problems.extend(compare(name, measure(art), golden, tolerances))
    for stale in sorted(set(entries) - set(artifacts_by_name)):
        problems.append(
            f"{stale}: budget entry no longer in the variant grid — "
            f"remove it (re-bless)"
        )
    return problems


def bless(artifacts_by_name: Optional[Dict[str, Dict]] = None,
          path: Optional[str] = None) -> Dict:
    """Measure the grid and (re)write the golden budgets file."""
    from olearning_sim_tpu.analysis import grid

    if artifacts_by_name is None:
        artifacts_by_name = grid.grid_artifacts()

    def entry(art):
        # The golden holds only ENFORCED facts: memory_analysis numbers
        # are backend/jaxlib-volatile and would churn every re-bless diff
        # (they still ride the check_all --json report via measure()).
        m = measure(art)
        m.pop("memory", None)
        return m

    budgets = {
        "_comment": (
            "Golden HLO budgets per round-program variant. Regenerate "
            "with `python scripts/check_all.py --bless` after an "
            "intentional program change and commit the diff "
            "(docs/static_analysis.md)."
        ),
        "tolerances": dict(DEFAULT_TOLERANCES),
        "variants": {
            name: entry(art)
            for name, art in sorted(artifacts_by_name.items())
        },
    }
    out = path or BUDGETS_PATH
    with open(out, "w", encoding="utf-8") as f:
        json.dump(budgets, f, indent=1, sort_keys=True)
        f.write("\n")
    return budgets


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--bless" in argv:
        budgets = bless()
        print(f"hlo_audit: blessed {len(budgets['variants'])} variants "
              f"-> {BUDGETS_PATH}")
        return 0
    problems = check()
    for p in problems:
        print(f"hlo_audit: {p}", file=sys.stderr)
    if problems:
        print(f"hlo_audit: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("hlo_audit: OK — grid within budgets")
    return 0


if __name__ == "__main__":
    # Standalone: a multi-device CPU platform must exist before jax init.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.exit(main())

"""Program-analysis suite: static guarantees over the compiled artifact.

The platform's core bet is that every behavior — deadline masks, attack
injection, robust aggregation, the sharded server update — compiles into
ONE XLA round program, so the compiled artifact (not the Python) is where
scale regressions hide. This package analyzes that artifact, plus the
repo's source, as *checks*:

- :mod:`~olearning_sim_tpu.analysis.grid` — the variant grid: every
  (program x shard_server_update x dp) combination AOT-lowered and
  compiled once per process, shared by the analyzers below.
- :mod:`~olearning_sim_tpu.analysis.hlo_audit` — per-variant budgets:
  collective bytes per kind, largest live buffer, dtype census (f64
  leakage), donation survival; diffed against the checked-in golden
  ``analysis/budgets.json``.
- :mod:`~olearning_sim_tpu.analysis.retrace` — the no-retrace guarantee:
  per-round scalar knobs (clip, deadline, attack scale, trim fraction)
  are data, never baked constants — one executable per variant.
- :mod:`~olearning_sim_tpu.analysis.ast_rules` — repo-invariant AST
  lints: wall-clock discipline, sqlite access routing, host-sync-free
  engine, no invisible exception swallows.

``scripts/check_all.py`` drives all of these (plus the four pre-existing
check scripts) under uniform exit codes and a JSON report; each module
also runs standalone via ``python -m olearning_sim_tpu.analysis.<mod>``.
See docs/static_analysis.md for the analyzer catalog, the budget
re-bless workflow, and the waiver policy.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


def run_analyzers(
    registry: Dict[str, Callable[[], List[str]]],
    only: Optional[List[str]] = None,
    skip: Optional[List[str]] = None,
) -> Dict[str, Dict]:
    """Run each ``name -> check()`` analyzer, timing it and catching
    internal errors, into a uniform report::

        {name: {"ok": bool, "problems": [...], "seconds": float,
                "error": str | None}}

    ``ok`` is False for both findings and crashes; ``error`` is set only
    when the analyzer itself raised (exit code 2 territory for drivers).
    """
    report: Dict[str, Dict] = {}
    for name, fn in registry.items():
        if only is not None and name not in only:
            continue
        if skip is not None and name in skip:
            continue
        t0 = time.perf_counter()
        problems: List[str] = []
        error = None
        try:
            problems = list(fn())
        except Exception as e:  # noqa: BLE001 — a crashed analyzer is a report entry
            error = f"{type(e).__name__}: {e}"
        report[name] = {
            "ok": error is None and not problems,
            "problems": problems,
            "seconds": round(time.perf_counter() - t0, 3),
            "error": error,
        }
    return report

"""The round-program variant grid: every compiled artifact the engine can
produce, AOT-lowered and compiled ONCE per process for the analyzers.

One :class:`Variant` names a point in (program structure x
``shard_server_update`` x dp). For each, :func:`artifacts` builds a tiny
fedcore (mlp2, 16 clients — shapes small enough that the whole grid
compiles in tens of seconds on CPU, structure identical to production
programs) and captures:

- ``lowered_a`` / ``lowered_b`` — the StableHLO of two
  ``FedCore.lower_round_step`` calls with DIFFERENT per-round scalar-knob
  values (clip finite vs disabled, deadline, trim fraction, attack
  scales). Identical text proves the knobs are data, not baked
  constants (analysis/retrace).
- ``same_fn`` / ``trace_count`` — the two knob settings resolved to the
  same compiled-function variant and traced it exactly once (the
  executable-cache-key half of the no-retrace guarantee; PR 5's
  literal-inf clip bug re-keyed exactly this cache).
- ``compiled`` — post-optimization HLO of the first lowering, plus
  ``memory`` stats (analysis/hlo_audit budgets).

Builds are cached process-wide so hlo_audit, retrace, and
check_hlo_collectives share one compile per variant (a full-grid run in
``scripts/check_all.py`` compiles each program exactly once).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

PROGRAMS = ("plain", "deadline", "attack", "defense", "maximal",
            "async", "async_defense")

# Programs audited on the model-parallel (mp=2) sub-grid. Gathering
# defenses / async are rejected at mp>1 (composition matrix in
# docs/performance.md), so the mp dimension covers the supported set:
# plain, deadline, attack, and clip-only defense ("clip" exists only
# here — at mp=1 clipping is part of the full "defense"/"maximal"
# programs).
MP_PROGRAMS = ("plain", "deadline", "attack", "clip")

# Models of the mp sub-grid: the mlp+cnn families prove the
# replicated-fallback path (tp_param_specs shards nothing -> the program
# must still meet the SAME budget discipline), distilbert proves the
# really-sharded tensor-parallel path on the grid's tiny text shapes.
MP_MODELS = ("mlp2", "cnn4", "distilbert")

# Buffer size for the async grid variants: 16 clients / M=4 -> a 4-window
# commit scan, so the compiled buffer structure (segment_sum + commit
# scan) is exercised with real multi-window data.
ASYNC_BUFFER = 4

# Global rows per stream block for the streamed ("stream") variants:
# 16 clients / 8 rows -> 2 stream blocks, so the audited partial program
# is the real multi-block shape (carry in, carry out). Streamed rounds
# run the replicated server update on dp-only meshes, so the stream
# sub-grid spans dp only (no shard_server_update axis — the composition
# matrix in docs/performance.md).
STREAM_ROWS = 8

NUM_CLIENTS = 16
INPUT_SHAPE = (8,)
NUM_CLASSES = 3
MODEL = "mlp2"
MODEL_OVERRIDES = {"hidden": [16], "num_classes": NUM_CLASSES}

# Per-model build shapes for the mp sub-grid (MODEL/MODEL_OVERRIDES stay
# the mp=1 grid's; mlp2 reuses them via the dict below so the two can
# never drift).
GRID_MODELS = {
    "mlp2": dict(input_shape=INPUT_SHAPE, text=False,
                 overrides=MODEL_OVERRIDES),
    "cnn4": dict(input_shape=(8, 8, 3), text=False,
                 overrides={"features": (4, 4, 8),
                            "num_classes": NUM_CLASSES}),
    "distilbert": dict(input_shape=(8,), text=True,
                       overrides={"vocab_size": 64, "max_len": 8,
                                  "width": 16, "depth": 2, "heads": 2,
                                  "mlp_dim": 32, "num_classes": 2}),
}


@dataclasses.dataclass(frozen=True)
class Variant:
    """One point of the grid; ``name`` keys budgets.json. The defaults
    (mp=1, mlp2) keep every pre-mp budget key byte-identical — the mp=1
    half of this file IS the PR 8 grid, so an unchanged budgets.json
    entry is the proof that mp wiring left the mp=1 programs alone."""

    program: str          # one of PROGRAMS (mp=1) / MP_PROGRAMS (mp>1)
    shard_server_update: bool
    dp: int
    mp: int = 1
    model: str = MODEL

    @property
    def name(self) -> str:
        base = (f"{self.program}/shard{int(self.shard_server_update)}"
                f"/dp{self.dp}")
        if self.mp > 1:
            base += f"/mp{self.mp}"
        if self.model != MODEL:
            base += f"/{self.model}"
        return base


def variant_grid(dps: Tuple[int, ...] = (1, 2),
                 programs: Iterable[str] = PROGRAMS,
                 include_mp: Optional[bool] = None) -> List[Variant]:
    """The full audit grid: (programs x shard_server_update x dp) at mp=1
    plus the model-parallel sub-grid (:func:`mp_variant_grid`).

    ``include_mp`` defaults to "only on the unfiltered grid": a caller
    narrowing ``dps``/``programs`` asked for a subset and must not get
    the fixed dp=2/mp=2 sub-grid appended behind its back (it could even
    exceed the host's device count); pass ``include_mp=True``/``False``
    to override either way."""
    if include_mp is None:
        include_mp = tuple(dps) == (1, 2) and tuple(programs) == PROGRAMS
    return [
        Variant(program=p, shard_server_update=s, dp=dp)
        for p in programs
        for s in (False, True)
        for dp in dps
    ] + (mp_variant_grid() + stream_variant_grid() if include_mp else [])


def stream_variant_grid(dps: Tuple[int, ...] = (1, 2)) -> List[Variant]:
    """The streamed sub-grid: the block-streamed PARTIAL program
    (``FedCore.stream_round``'s per-block step, the one executed
    population/stream_rows times per round) audited under the same
    budget/retrace discipline. One program per dp — streaming has no
    shard_server_update axis (replicated update only)."""
    return [Variant(program="stream", shard_server_update=False, dp=dp)
            for dp in dps]


def mp_variant_grid(mp: int = 2, dp: int = 2) -> List[Variant]:
    """The mp>1 sub-grid: the GSPMD-auto round program audited under the
    same budget discipline as the manual one. Per model: the plain
    program with both server-update layouts (the mp x shard_server_update
    composition this PR unlocks), plus deadline/attack/clip with the
    replicated update for mlp2 — enough to probe every mp-supported
    program structure without doubling the grid's compile time."""
    variants = []
    for model in MP_MODELS:
        for s in (False, True):
            variants.append(Variant(program="plain", shard_server_update=s,
                                    dp=dp, mp=mp, model=model))
    for p in ("deadline", "attack", "clip"):
        variants.append(Variant(program=p, shard_server_update=False,
                                dp=dp, mp=mp, model="mlp2"))
    return variants


_CORES: Dict[Tuple[bool, int, int, str], tuple] = {}
_ARTIFACTS: Dict[str, Dict] = {}


def _core_state_ds(shard: bool, dp: int, mp: int = 1, model: str = MODEL):
    """A (core, state, dataset) triple per (shard_server_update, dp, mp,
    model), cached — every program variant of that tuple reuses one
    build."""
    key = (shard, dp, mp, model)
    if key in _CORES:
        return _CORES[key]
    import jax

    from olearning_sim_tpu.engine import build_fedcore, fedavg
    from olearning_sim_tpu.engine.client_data import (
        make_synthetic_dataset,
        make_synthetic_text_dataset,
    )
    from olearning_sim_tpu.engine.fedcore import FedCoreConfig
    from olearning_sim_tpu.parallel.mesh import make_mesh_plan

    devices = jax.devices()
    if len(devices) < dp * mp:
        raise RuntimeError(
            f"variant grid needs {dp * mp} devices, have {len(devices)}; "
            f"set --xla_force_host_platform_device_count (conftest/"
            f"check_all do this before jax initializes)"
        )
    spec = GRID_MODELS[model]
    plan = make_mesh_plan(devices=devices[:dp * mp], dp=dp, mp=mp)
    cfg = FedCoreConfig(batch_size=4, max_local_steps=2, block_clients=2,
                        shard_server_update=shard)
    core = build_fedcore(
        model, fedavg(0.1), plan, cfg,
        model_overrides=dict(spec["overrides"]),
        input_shape=spec["input_shape"],
    )
    if spec["text"]:
        ds = make_synthetic_text_dataset(
            seed=0, num_clients=NUM_CLIENTS, n_local=6,
            seq_len=spec["input_shape"][0], num_classes=2,
            vocab_size=spec["overrides"]["vocab_size"],
        )
    else:
        ds = make_synthetic_dataset(
            0, NUM_CLIENTS, 6, spec["input_shape"], NUM_CLASSES
        )
    ds = ds.pad_for(plan, cfg.block_clients).place(plan)
    state = core.init_state(jax.random.key(0))
    _CORES[key] = (core, state, ds)
    return _CORES[key]


def _knob_kwargs(program: str, core, ds, setting: str) -> Dict:
    """round_step kwargs for knob setting "a" or "b" of one program.
    The two settings differ in EVERY per-round scalar knob the variant
    exposes — including clip finite-vs-disabled, the exact transition
    that once re-keyed the executable cache (fedcore.py sentinel note)."""
    import numpy as np

    from olearning_sim_tpu.engine.defense import DefenseConfig
    from olearning_sim_tpu.parallel.mesh import global_put

    b = setting == "b"
    kwargs: Dict = {}
    if program in ("async", "async_defense"):
        # Buffered async rounds: the two settings differ in EVERY
        # per-round data input — arrival order (window assignments),
        # staleness_alpha, and max_staleness (disabled vs binding) — while
        # M (the structural knob) stays fixed, so both must resolve to
        # one compiled program.
        from olearning_sim_tpu.engine.async_rounds import (
            AsyncConfig,
            plan_async_round,
        )

        acfg = AsyncConfig(
            buffer_size=ASYNC_BUFFER,
            staleness_alpha=0.5 if not b else 1.5,
            max_staleness=None if not b else 2,
            schedule="polynomial",
        )
        completion = np.linspace(
            0.2, 3.0 if not b else 9.0, ds.num_clients
        ).astype(np.float32)
        if b:
            completion = completion[::-1].copy()  # reversed arrival order
        kwargs["async_plan"] = plan_async_round(
            acfg, completion, np.ones(ds.num_clients, bool), ds.num_clients
        )
    if program == "async_defense":
        kwargs["defense"] = DefenseConfig(
            clip_norm=5.0 if not b else None,  # None = disabled sentinel
            aggregator="trimmed_mean",
            trim_fraction=0.1 if not b else 0.4,
            anomaly_threshold=4.0,
        )
        return kwargs
    if program == "async":
        return kwargs
    if program in ("deadline", "maximal"):
        completion = np.linspace(
            0.2, 3.0 if not b else 9.0, ds.num_clients
        ).astype(np.float32)
        kwargs["completion_time"] = global_put(
            completion, core.plan.client_sharding()
        )
        kwargs["deadline"] = 1.75 if not b else 0.5
    if program in ("attack", "maximal"):
        scale = np.ones((ds.num_clients,), np.float32)
        scale[: ds.num_clients // 4] = -1.0 if not b else 7.5
        kwargs["attack_scale"] = global_put(
            scale, core.plan.client_sharding()
        )
    if program in ("defense", "maximal"):
        kwargs["defense"] = DefenseConfig(
            clip_norm=5.0 if not b else None,  # None = disabled sentinel
            aggregator="trimmed_mean",
            trim_fraction=0.1 if not b else 0.4,
            anomaly_threshold=4.0,
        )
    if program == "clip":
        # The one defense shape an mp>1 mesh supports: streaming L2 delta
        # clipping, no gather. Both settings keep the defense ENABLED
        # (clip_norm=None would disable it and correctly resolve to the
        # plain program — a different variant, not a knob change); the
        # binding-vs-astronomical pair probes that the norm is data.
        kwargs["defense"] = DefenseConfig(
            clip_norm=5.0 if not b else 1.0e9,
            aggregator="mean",
        )
    return kwargs


def _stream_artifacts(variant: Variant) -> Dict:
    """Artifacts for one streamed variant: the block-streamed PARTIAL
    program AOT-lowered twice with different per-round DATA (masks, step
    counts) — identical lowerings + one trace prove stream/scenario knobs
    never retrace, and the compiled text feeds the same budget audit."""
    import jax
    import numpy as np

    from olearning_sim_tpu.engine.client_data import (
        HostClientStore,
        make_synthetic_dataset,
    )

    core, state, _ = _core_state_ds(False, variant.dp, 1, MODEL)
    host = make_synthetic_dataset(
        0, NUM_CLIENTS, 6, INPUT_SHAPE, NUM_CLASSES
    ).pad_for(core.plan, core.config.block_clients)
    store = HostClientStore.from_dataset(host)

    def knobs(setting):
        b = setting == "b"
        rng = np.random.default_rng(2 if b else 1)
        return dict(
            participate=(rng.random(host.num_clients)
                         < (0.4 if b else 0.7)).astype(np.float32),
            num_steps=rng.integers(
                1, 3, host.num_clients
            ).astype(np.int32),
        )

    lowered = core.lower_stream_step(state, store, STREAM_ROWS,
                                     **knobs("a"))
    n_variants = len(core._stream_variants)
    lowered_b = core.lower_stream_step(state, store, STREAM_ROWS,
                                       **knobs("b"))
    same_fn = len(core._stream_variants) == n_variants
    rpd = STREAM_ROWS // variant.dp
    trace_count = core.trace_counts.get(
        ("stream", rpd, False, False, None), 0
    )

    compiled = lowered.compile()
    try:
        mem = compiled.memory_analysis()
        memory = {
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
            "alias_bytes": int(getattr(mem, "alias_size_in_bytes", 0)),
        }
    except Exception:  # noqa: BLE001 — memory stats are best-effort
        memory = None
    params_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(state.params)
    )
    return {
        "variant": variant.name,
        "program": variant.program,
        "dp": variant.dp,
        "mp": variant.mp,
        "model": variant.model,
        "shard_server_update": variant.shard_server_update,
        "lowered_a": lowered.as_text(),
        "lowered_b": lowered_b.as_text(),
        "same_fn": same_fn,
        "trace_count": trace_count,
        "compiled": compiled.as_text(),
        "memory": memory,
        "params_bytes": params_bytes,
        "clients": host.num_clients,
    }


def artifacts(variant: Variant) -> Dict:
    """Lowered/compiled artifacts for one variant (process-cached)."""
    if variant.name in _ARTIFACTS:
        return _ARTIFACTS[variant.name]
    if variant.program == "stream":
        art = _stream_artifacts(variant)
        _ARTIFACTS[variant.name] = art
        return art
    import jax

    core, state, ds = _core_state_ds(variant.shard_server_update, variant.dp,
                                     variant.mp, variant.model)

    kwargs_a = _knob_kwargs(variant.program, core, ds, "a")
    fn_a, args_a = core._prepare_round_args(state, ds, **kwargs_a)
    fn_b, args_b = core._prepare_round_args(
        state, ds, **_knob_kwargs(variant.program, core, ds, "b")
    )
    lowered = fn_a.lower(*args_a)
    lowered_b = fn_b.lower(*args_b)
    # The trace-count probe: mirror _prepare_round_args' variant key and
    # read how many times this variant's body was traced — 1 iff the
    # second knob setting hit the cached trace (the executable-cache-key
    # guarantee; a retrace would bump it to 2).
    if "async_plan" in kwargs_a:
        from olearning_sim_tpu.engine.async_rounds import async_variant_key

        ap = kwargs_a["async_plan"]
        key = async_variant_key(
            ap.num_windows, ap.config.schedule,
            "attack_scale" in kwargs_a,
            kwargs_a.get("defense"),
        )
    else:
        key = (
            "deadline" in kwargs_a, "attack_scale" in kwargs_a,
            kwargs_a["defense"].structure_key
            if "defense" in kwargs_a else None,
        )
    trace_count = core.trace_counts.get(key, 0)

    compiled = lowered.compile()
    compiled_text = compiled.as_text()
    try:
        mem = compiled.memory_analysis()
        memory = {
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
            "alias_bytes": int(getattr(mem, "alias_size_in_bytes", 0)),
        }
    except Exception:  # noqa: BLE001 — memory stats are best-effort per backend
        memory = None

    params_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(state.params)
    )
    art = {
        "variant": variant.name,
        "program": variant.program,
        "dp": variant.dp,
        "mp": variant.mp,
        "model": variant.model,
        "shard_server_update": variant.shard_server_update,
        "lowered_a": lowered.as_text(),
        "lowered_b": lowered_b.as_text(),
        "same_fn": fn_a is fn_b,
        "trace_count": trace_count,
        "compiled": compiled_text,
        "memory": memory,
        "params_bytes": params_bytes,
        "clients": ds.num_clients,
    }
    _ARTIFACTS[variant.name] = art
    return art


def grid_artifacts(
    variants: Optional[List[Variant]] = None,
    progress=None,
) -> Dict[str, Dict]:
    """Artifacts for the whole grid, keyed by variant name."""
    out = {}
    for v in variants if variants is not None else variant_grid():
        if progress is not None:
            progress(v.name)
        out[v.name] = artifacts(v)
    return out


def reset_cache() -> None:
    """Drop cached cores/artifacts (tests that fork platform config)."""
    _CORES.clear()
    _ARTIFACTS.clear()

"""The round-program variant grid: every compiled artifact the engine can
produce, AOT-lowered and compiled ONCE per process for the analyzers.

One :class:`Variant` names a point in (program structure x
``shard_server_update`` x dp). For each, :func:`artifacts` builds a tiny
fedcore (mlp2, 16 clients — shapes small enough that the whole grid
compiles in tens of seconds on CPU, structure identical to production
programs) and captures:

- ``lowered_a`` / ``lowered_b`` — the StableHLO of two
  ``FedCore.lower_round_step`` calls with DIFFERENT per-round scalar-knob
  values (clip finite vs disabled, deadline, trim fraction, attack
  scales). Identical text proves the knobs are data, not baked
  constants (analysis/retrace).
- ``same_fn`` / ``trace_count`` — the two knob settings resolved to the
  same compiled-function variant and traced it exactly once (the
  executable-cache-key half of the no-retrace guarantee; PR 5's
  literal-inf clip bug re-keyed exactly this cache).
- ``compiled`` — post-optimization HLO of the first lowering, plus
  ``memory`` stats (analysis/hlo_audit budgets).

Builds are cached process-wide so hlo_audit, retrace, and
check_hlo_collectives share one compile per variant (a full-grid run in
``scripts/check_all.py`` compiles each program exactly once).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

PROGRAMS = ("plain", "deadline", "attack", "defense", "maximal",
            "async", "async_defense")

# Buffer size for the async grid variants: 16 clients / M=4 -> a 4-window
# commit scan, so the compiled buffer structure (segment_sum + commit
# scan) is exercised with real multi-window data.
ASYNC_BUFFER = 4

NUM_CLIENTS = 16
INPUT_SHAPE = (8,)
NUM_CLASSES = 3
MODEL = "mlp2"
MODEL_OVERRIDES = {"hidden": [16], "num_classes": NUM_CLASSES}


@dataclasses.dataclass(frozen=True)
class Variant:
    """One point of the grid; ``name`` keys budgets.json."""

    program: str          # one of PROGRAMS
    shard_server_update: bool
    dp: int

    @property
    def name(self) -> str:
        return (f"{self.program}/shard{int(self.shard_server_update)}"
                f"/dp{self.dp}")


def variant_grid(dps: Tuple[int, ...] = (1, 2),
                 programs: Iterable[str] = PROGRAMS) -> List[Variant]:
    """The full audit grid: programs x shard_server_update x dp."""
    return [
        Variant(program=p, shard_server_update=s, dp=dp)
        for p in programs
        for s in (False, True)
        for dp in dps
    ]


_CORES: Dict[Tuple[bool, int], tuple] = {}
_ARTIFACTS: Dict[str, Dict] = {}


def _core_state_ds(shard: bool, dp: int):
    """A (core, state, dataset) triple per (shard_server_update, dp),
    cached — every program variant of that pair reuses one build."""
    key = (shard, dp)
    if key in _CORES:
        return _CORES[key]
    import jax

    from olearning_sim_tpu.engine import build_fedcore, fedavg
    from olearning_sim_tpu.engine.client_data import make_synthetic_dataset
    from olearning_sim_tpu.engine.fedcore import FedCoreConfig
    from olearning_sim_tpu.parallel.mesh import make_mesh_plan

    devices = jax.devices()
    if len(devices) < dp:
        raise RuntimeError(
            f"variant grid needs {dp} devices, have {len(devices)}; set "
            f"--xla_force_host_platform_device_count (conftest/check_all "
            f"do this before jax initializes)"
        )
    plan = make_mesh_plan(devices=devices[:dp], dp=dp, mp=1)
    cfg = FedCoreConfig(batch_size=4, max_local_steps=2, block_clients=2,
                        shard_server_update=shard)
    core = build_fedcore(
        MODEL, fedavg(0.1), plan, cfg,
        model_overrides=dict(MODEL_OVERRIDES), input_shape=INPUT_SHAPE,
    )
    ds = make_synthetic_dataset(
        0, NUM_CLIENTS, 6, INPUT_SHAPE, NUM_CLASSES
    ).pad_for(plan, cfg.block_clients).place(plan)
    state = core.init_state(jax.random.key(0))
    _CORES[key] = (core, state, ds)
    return _CORES[key]


def _knob_kwargs(program: str, core, ds, setting: str) -> Dict:
    """round_step kwargs for knob setting "a" or "b" of one program.
    The two settings differ in EVERY per-round scalar knob the variant
    exposes — including clip finite-vs-disabled, the exact transition
    that once re-keyed the executable cache (fedcore.py sentinel note)."""
    import numpy as np

    from olearning_sim_tpu.engine.defense import DefenseConfig
    from olearning_sim_tpu.parallel.mesh import global_put

    b = setting == "b"
    kwargs: Dict = {}
    if program in ("async", "async_defense"):
        # Buffered async rounds: the two settings differ in EVERY
        # per-round data input — arrival order (window assignments),
        # staleness_alpha, and max_staleness (disabled vs binding) — while
        # M (the structural knob) stays fixed, so both must resolve to
        # one compiled program.
        from olearning_sim_tpu.engine.async_rounds import (
            AsyncConfig,
            plan_async_round,
        )

        acfg = AsyncConfig(
            buffer_size=ASYNC_BUFFER,
            staleness_alpha=0.5 if not b else 1.5,
            max_staleness=None if not b else 2,
            schedule="polynomial",
        )
        completion = np.linspace(
            0.2, 3.0 if not b else 9.0, ds.num_clients
        ).astype(np.float32)
        if b:
            completion = completion[::-1].copy()  # reversed arrival order
        kwargs["async_plan"] = plan_async_round(
            acfg, completion, np.ones(ds.num_clients, bool), ds.num_clients
        )
    if program == "async_defense":
        kwargs["defense"] = DefenseConfig(
            clip_norm=5.0 if not b else None,  # None = disabled sentinel
            aggregator="trimmed_mean",
            trim_fraction=0.1 if not b else 0.4,
            anomaly_threshold=4.0,
        )
        return kwargs
    if program == "async":
        return kwargs
    if program in ("deadline", "maximal"):
        completion = np.linspace(
            0.2, 3.0 if not b else 9.0, ds.num_clients
        ).astype(np.float32)
        kwargs["completion_time"] = global_put(
            completion, core.plan.client_sharding()
        )
        kwargs["deadline"] = 1.75 if not b else 0.5
    if program in ("attack", "maximal"):
        scale = np.ones((ds.num_clients,), np.float32)
        scale[: ds.num_clients // 4] = -1.0 if not b else 7.5
        kwargs["attack_scale"] = global_put(
            scale, core.plan.client_sharding()
        )
    if program in ("defense", "maximal"):
        kwargs["defense"] = DefenseConfig(
            clip_norm=5.0 if not b else None,  # None = disabled sentinel
            aggregator="trimmed_mean",
            trim_fraction=0.1 if not b else 0.4,
            anomaly_threshold=4.0,
        )
    return kwargs


def artifacts(variant: Variant) -> Dict:
    """Lowered/compiled artifacts for one variant (process-cached)."""
    if variant.name in _ARTIFACTS:
        return _ARTIFACTS[variant.name]
    import jax

    core, state, ds = _core_state_ds(variant.shard_server_update, variant.dp)

    kwargs_a = _knob_kwargs(variant.program, core, ds, "a")
    fn_a, args_a = core._prepare_round_args(state, ds, **kwargs_a)
    fn_b, args_b = core._prepare_round_args(
        state, ds, **_knob_kwargs(variant.program, core, ds, "b")
    )
    lowered = fn_a.lower(*args_a)
    lowered_b = fn_b.lower(*args_b)
    # The trace-count probe: mirror _prepare_round_args' variant key and
    # read how many times this variant's body was traced — 1 iff the
    # second knob setting hit the cached trace (the executable-cache-key
    # guarantee; a retrace would bump it to 2).
    if "async_plan" in kwargs_a:
        from olearning_sim_tpu.engine.async_rounds import async_variant_key

        ap = kwargs_a["async_plan"]
        key = async_variant_key(
            ap.num_windows, ap.config.schedule,
            "attack_scale" in kwargs_a,
            kwargs_a.get("defense"),
        )
    else:
        key = (
            "deadline" in kwargs_a, "attack_scale" in kwargs_a,
            kwargs_a["defense"].structure_key
            if "defense" in kwargs_a else None,
        )
    trace_count = core.trace_counts.get(key, 0)

    compiled = lowered.compile()
    compiled_text = compiled.as_text()
    try:
        mem = compiled.memory_analysis()
        memory = {
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
            "alias_bytes": int(getattr(mem, "alias_size_in_bytes", 0)),
        }
    except Exception:  # noqa: BLE001 — memory stats are best-effort per backend
        memory = None

    params_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(state.params)
    )
    art = {
        "variant": variant.name,
        "program": variant.program,
        "dp": variant.dp,
        "shard_server_update": variant.shard_server_update,
        "lowered_a": lowered.as_text(),
        "lowered_b": lowered_b.as_text(),
        "same_fn": fn_a is fn_b,
        "trace_count": trace_count,
        "compiled": compiled_text,
        "memory": memory,
        "params_bytes": params_bytes,
        "clients": ds.num_clients,
    }
    _ARTIFACTS[variant.name] = art
    return art


def grid_artifacts(
    variants: Optional[List[Variant]] = None,
    progress=None,
) -> Dict[str, Dict]:
    """Artifacts for the whole grid, keyed by variant name."""
    out = {}
    for v in variants if variants is not None else variant_grid():
        if progress is not None:
            progress(v.name)
        out[v.name] = artifacts(v)
    return out


def reset_cache() -> None:
    """Drop cached cores/artifacts (tests that fork platform config)."""
    _CORES.clear()
    _ARTIFACTS.clear()

"""Retrace / constant-leak detector: per-round scalar knobs must be data.

PR 5 learned this the hard way: a literal ``inf`` clip value re-keyed the
jit executable cache, so toggling clipping recompiled the round program
(seconds to minutes, per toggle, silently). The fix made every scalar
knob — clip norm, trim fraction, deadline, attack scales — a traced
input, asserted by a one-off ``FedCore.trace_counts`` probe on the one
defended program. This analyzer generalizes that probe to the WHOLE
variant grid as a static check:

For every variant, analysis/grid resolves and AOT-lowers the round
program twice with different knob values (clip 5.0 vs disabled, deadline
1.75 vs 0.5, trim 0.1 vs 0.4, attack scales -1 vs 7.5). The guarantee
has three layers, each failing independently:

1. **Same compiled function** — both knob settings must resolve to the
   same ``_round_step_variants`` cache entry; a knob leaking into the
   variant KEY means every value change rebuilds the program.
2. **One trace** — ``trace_counts`` for the variant stays at 1 after both
   lowerings; a second trace means jax saw different avals (the
   executable-cache-key regression: e.g. a weak-typed Python scalar
   changing type between rounds).
3. **Identical lowering** — the two StableHLO texts must be byte-equal; a
   knob baked as ``stablehlo.constant`` produces a textual diff even when
   the avals happen to agree.

Standalone: ``python -m olearning_sim_tpu.analysis.retrace``.
"""

from __future__ import annotations

import itertools
import os
import sys
from typing import Dict, List, Optional


def _first_diff(a: str, b: str, context: int = 1) -> str:
    """A one-line pointer at the first differing line (for findings)."""
    for i, (la, lb) in enumerate(itertools.zip_longest(
            a.splitlines(), b.splitlines(), fillvalue="<eof>")):
        if la != lb:
            marker = ""
            if "constant" in la or "constant" in lb:
                marker = " (a baked constant — the knob is compile-time)"
            return (f"first diff at lowered line {i + 1}{marker}: "
                    f"{la.strip()[:120]!r} vs {lb.strip()[:120]!r}")
    return "texts differ only in length"


def compare_variant(art: Dict) -> List[str]:
    """Findings for one variant's grid artifacts (empty = clean)."""
    name = art["variant"]
    problems = []
    if not art["same_fn"]:
        problems.append(
            f"{name}: the two knob settings resolved to DIFFERENT "
            f"compiled functions — a per-round scalar knob is part of the "
            f"program-variant key (every value change rebuilds the "
            f"program; keep knobs out of _round_step_variants keys)"
        )
    if art["trace_count"] != 1:
        problems.append(
            f"{name}: round program traced {art['trace_count']} times "
            f"across two knob settings (must be exactly 1) — the jit "
            f"executable cache was re-keyed; check that every scalar knob "
            f"enters as a committed jnp array, not a Python literal"
        )
    if art["lowered_a"] != art["lowered_b"]:
        problems.append(
            f"{name}: lowered programs differ between knob settings — a "
            f"knob was baked into the traced program as a constant; "
            f"{_first_diff(art['lowered_a'], art['lowered_b'])}"
        )
    return problems


def check(artifacts_by_name: Optional[Dict[str, Dict]] = None) -> List[str]:
    """Retrace findings across the whole grid (empty = clean)."""
    from olearning_sim_tpu.analysis import grid

    if artifacts_by_name is None:
        artifacts_by_name = grid.grid_artifacts()
    problems: List[str] = []
    for _, art in sorted(artifacts_by_name.items()):
        problems.extend(compare_variant(art))
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    problems = check()
    for p in problems:
        print(f"retrace: {p}", file=sys.stderr)
    if problems:
        print(f"retrace: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("retrace: OK — one executable per variant across knob settings")
    return 0


if __name__ == "__main__":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.exit(main())

"""Repo-invariant AST lints: the invariants this codebase learned the
hard way, enforced so they stay learned.

Rules (each with its waiver marker; see WAIVERS for the policy):

- **wall-clock** — no ``time.time()`` outside ``utils/clocks.py``.
  Wall-clock steps (NTP, manual set, VM migration) once made polling
  barriers stall or expire instantly (PR 3); interval math must use
  ``utils.clocks``. The ONLY legitimate wall-clock sites are the
  persisted lease/queue timestamps compared ACROSS processes (monotonic
  clocks have per-process epochs) — those carry explicit waivers.
- **sqlite-connect** — no ``sqlite3.connect`` outside ``utils/repo.py``.
  Raw connections skip WAL + busy_timeout and deadlock concurrent
  writers (PR 4 routed every site through ``connect_sqlite``).
- **host-sync** — no ``jax.device_get`` / ``.block_until_ready`` inside
  ``engine/fedcore.py`` / ``engine/defense.py``. The compiled round
  program must stay async-dispatchable; host syncs belong in the runner,
  which accounts them as the ``host_transfer`` phase.
- **silent-except** — no ``except Exception: pass`` (or bare /
  ``BaseException``) without a waiver. An invisible swallow turned
  degraded-path failures into unobservable no-ops more than once; either
  narrow it, log it, or waive it with a rationale.

Waiver policy: a flagged line is waived ONLY when (a) the line (or its
neighbor) carries the rule's marker comment AND (b) the file is listed in
WAIVERS with a rationale. A marker in an unlisted file, or a WAIVERS
entry with no live marker, is itself a violation — intentional sites are
documented, not invisible, and the table cannot rot.

Standalone: ``python -m olearning_sim_tpu.analysis.ast_rules``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
PKG_NAME = "olearning_sim_tpu"

# Built by concatenation so this module's own strings never read as live
# waiver markers to the orphan-marker scan.
_M = "lint: " + "allow-"
MARKERS = {
    "wall-clock": _M + "wall-clock",
    "sqlite-connect": _M + "sqlite",
    "host-sync": _M + "host-sync",
    "silent-except": _M + "silent",
}

# Files a rule never applies to (the blessed implementation homes).
EXEMPT = {
    "wall-clock": {"olearning_sim_tpu/utils/clocks.py"},
    "sqlite-connect": {"olearning_sim_tpu/utils/repo.py"},
}

# host-sync applies ONLY inside the compiled-program modules.
HOST_SYNC_SCOPE = (
    "olearning_sim_tpu/engine/fedcore.py",
    "olearning_sim_tpu/engine/defense.py",
)

# rule -> {repo-relative file: rationale}. The ONLY files where that
# rule's marker is legal; every entry must have at least one live marker.
WAIVERS: Dict[str, Dict[str, str]] = {
    "wall-clock": {
        "olearning_sim_tpu/taskmgr/task_repo.py":
            "lease claim/renew/expiry timestamps are persisted in the task "
            "table and compared across processes; monotonic clocks have "
            "per-process epochs, so cross-process lease math MUST be "
            "wall-clock",
        "olearning_sim_tpu/taskmgr/task_manager.py":
            "heartbeat renewal and the interrupt watchdog compare against "
            "repo-persisted wall-clock lease/queue timestamps written by "
            "other processes",
        "olearning_sim_tpu/supervisor/supervisor.py":
            "lease-expiry scans compare repo-persisted wall-clock "
            "timestamps written by the owning worker process",
        "olearning_sim_tpu/taskmgr/pool.py":
            "planned migration renews the cross-process wall-clock lease "
            "and stamps the durable supervision ledger's last_resume_ts, "
            "both compared by other processes (supervisor backoff math)",
    },
    "silent-except": {
        "olearning_sim_tpu/utils/repo.py":
            "rollback/close during connection recycling: cleanup of an "
            "already-failed connection; the original error is re-raised "
            "after the second attempt",
        "olearning_sim_tpu/engine/compile_cache.py":
            "platform probe and telemetry bridge must never break "
            "compiles; the degraded answer (env value / uncounted event) "
            "is the designed fallback",
        "olearning_sim_tpu/supervisor/supervisor.py":
            "a deviceflow hiccup during finalization must not block it "
            "forever; the scan retries on a later pass",
    },
    "sqlite-connect": {},
    "host-sync": {
        "olearning_sim_tpu/engine/fedcore.py":
            "stream_round's per-client loss assembly is the streamed "
            "round's designed host sync point: it runs AFTER the final "
            "block and the finalize commit are dispatched, gathering the "
            "per-block device losses into the host [C] array the caller "
            "would otherwise device_get itself — the streamed analogue "
            "of the runner's host_transfer phase, placed here because "
            "the losses are per-block arrays private to the stream walk",
    },
}


def _py_files(root: str):
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


class _ImportMap(ast.NodeVisitor):
    """local alias -> module ("import time as t"), and
    local name -> (module, original) ("from time import time")."""

    def __init__(self):
        self.modules: Dict[str, str] = {}
        self.froms: Dict[str, Tuple[str, str]] = {}

    def visit_Import(self, node):
        for a in node.names:
            self.modules[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node):
        for a in node.names:
            if node.module:
                self.froms[a.asname or a.name] = (node.module, a.name)


def _is_module_call(node: ast.Call, imports: _ImportMap,
                    module: str, attr: str) -> bool:
    """``module.attr(...)`` through any alias, or ``from module import
    attr`` used bare."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == attr \
            and isinstance(f.value, ast.Name) \
            and imports.modules.get(f.value.id) == module:
        return True
    if isinstance(f, ast.Name) \
            and imports.froms.get(f.id) == (module, attr):
        return True
    return False


def _is_silent_handler(node: ast.ExceptHandler) -> bool:
    """``except [Exception|BaseException|<bare>]: pass`` exactly."""
    if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
        return False
    t = node.type
    if t is None:
        return True
    names = []
    for n in ast.walk(t):  # covers Name, Attribute tails, and tuples
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in ("Exception", "BaseException") for n in names)


def lint_source(src: str, relpath: str) -> List[Dict]:
    """All rule hits in one file's source, waivers NOT yet applied:
    ``[{"rule", "line", "message"}]``. ``check()`` applies the waiver
    policy on top; tests feed planted snippets straight in."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [{"rule": "parse", "line": e.lineno or 0,
                 "message": f"unparseable: {e.msg}"}]
    imports = _ImportMap()
    imports.visit(tree)
    hits: List[Dict] = []
    in_scope_host = relpath in HOST_SYNC_SCOPE
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if relpath not in EXEMPT["wall-clock"] \
                    and _is_module_call(node, imports, "time", "time"):
                hits.append({
                    "rule": "wall-clock", "line": node.lineno,
                    "message": "time.time() outside utils/clocks.py — use "
                               "utils.clocks for interval math, or waive "
                               "a genuine cross-process wall-clock site",
                })
            if relpath not in EXEMPT["sqlite-connect"] \
                    and _is_module_call(node, imports, "sqlite3", "connect"):
                hits.append({
                    "rule": "sqlite-connect", "line": node.lineno,
                    "message": "raw sqlite3.connect outside utils/repo.py "
                               "— route through utils.repo.connect_sqlite "
                               "(WAL + busy_timeout)",
                })
            if in_scope_host:
                f = node.func
                if _is_module_call(node, imports, "jax", "device_get") \
                        or (isinstance(f, ast.Attribute)
                            and f.attr == "block_until_ready"):
                    hits.append({
                        "rule": "host-sync", "line": node.lineno,
                        "message": "host sync inside the compiled-program "
                                   "module — device_get/block_until_ready "
                                   "belong in the runner (host_transfer "
                                   "phase)",
                    })
        elif isinstance(node, ast.ExceptHandler) \
                and _is_silent_handler(node):
            hits.append({
                "rule": "silent-except", "line": node.lineno,
                "message": "except Exception: pass — narrow it, log it, or "
                           "waive it with a rationale (degraded paths must "
                           "be observable)",
            })
    return hits


def _marker_lines(lines: List[str], marker: str) -> List[int]:
    """1-based line numbers whose comment text carries the marker."""
    out = []
    for i, line in enumerate(lines, 1):
        if "#" in line and marker in line.split("#", 1)[1]:
            out.append(i)
    return out


def check(pkg_root: Optional[str] = None,
          waivers: Optional[Dict[str, Dict[str, str]]] = None) -> List[str]:
    """Lint the whole package, applying the waiver policy; returns
    findings (empty = clean)."""
    root = pkg_root or os.path.join(REPO, PKG_NAME)
    waivers = WAIVERS if waivers is None else waivers
    self_rel = f"{PKG_NAME}/analysis/ast_rules.py"
    problems: List[str] = []
    used_waiver_files = {rule: set() for rule in MARKERS}
    for path in _py_files(root):
        rel = os.path.relpath(path, os.path.dirname(root)).replace(
            os.sep, "/")
        with open(path, encoding="utf-8") as f:
            src = f.read()
        lines = src.splitlines()
        marker_lines = {rule: set(_marker_lines(lines, marker))
                        for rule, marker in MARKERS.items()}
        consumed: set = set()
        for hit in lint_source(src, rel):
            rule = hit["rule"]
            if rule == "parse":
                problems.append(f"{rel}:{hit['line']}: {hit['message']}")
                continue
            # A marker waives the flagged line itself, the line after
            # (the `pass` of an except), or a comment up to two lines
            # above (rationales are usually two-line comment blocks).
            window = [n for n in (hit["line"] - 2, hit["line"] - 1,
                                  hit["line"], hit["line"] + 1)
                      if n in marker_lines[rule]]
            if window and rel in waivers.get(rule, {}):
                used_waiver_files[rule].add(rel)
                consumed.update((rule, n) for n in window)
                continue
            if window:
                problems.append(
                    f"{rel}:{hit['line']}: [{rule}] waiver marker present "
                    f"but {rel} is not in the ast_rules WAIVERS table — "
                    f"document the rationale there"
                )
                consumed.update((rule, n) for n in window)
                continue
            problems.append(
                f"{rel}:{hit['line']}: [{rule}] {hit['message']}"
            )
        # Orphan markers: a waiver comment with no flagged site right
        # there is stale documentation (the code it excused is gone).
        if rel == self_rel:
            continue
        for rule in MARKERS:
            for n in sorted(marker_lines[rule]):
                if (rule, n) not in consumed:
                    problems.append(
                        f"{rel}:{n}: [{rule}] stale waiver marker — no "
                        f"flagged site within one line; remove it"
                    )
    for rule, table in waivers.items():
        for rel in sorted(set(table) - used_waiver_files.get(rule, set())):
            problems.append(
                f"{rel}: [{rule}] WAIVERS entry has no live waived site — "
                f"remove the table entry"
            )
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    problems = check()
    for p in problems:
        print(f"ast_rules: {p}", file=sys.stderr)
    if problems:
        print(f"ast_rules: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("ast_rules: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

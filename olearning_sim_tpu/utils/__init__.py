from olearning_sim_tpu.utils.repo import (
    MemoryTableRepo,
    MySqlTableRepo,
    SqliteTableRepo,
    TableRepo,
)
from olearning_sim_tpu.utils.logging import Logger

__all__ = ["Logger", "MemoryTableRepo", "MySqlTableRepo", "SqliteTableRepo",
           "TableRepo"]

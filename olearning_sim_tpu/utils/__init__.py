from olearning_sim_tpu.utils.clocks import Deadline, monotonic
from olearning_sim_tpu.utils.repo import (
    MemoryTableRepo,
    MySqlTableRepo,
    SqliteTableRepo,
    TableRepo,
)
from olearning_sim_tpu.utils.logging import Logger

__all__ = ["Deadline", "Logger", "MemoryTableRepo", "MySqlTableRepo",
           "SqliteTableRepo", "TableRepo", "monotonic"]

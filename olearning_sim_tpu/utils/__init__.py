from olearning_sim_tpu.utils.repo import MemoryTableRepo, SqliteTableRepo, TableRepo
from olearning_sim_tpu.utils.logging import Logger

__all__ = ["Logger", "MemoryTableRepo", "SqliteTableRepo", "TableRepo"]

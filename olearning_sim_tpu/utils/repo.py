"""State repositories.

The reference persists every piece of control-plane state in MySQL via a
generic table accessor (``ols_core/utils/repo_utils.py:19-400`` SqlDataBase,
specialized as TaskTableRepo / ResTableRepo / the deviceflow table). The
rebuild keeps the same narrow interface but behind an ABC with two default
implementations:

- :class:`MemoryTableRepo` — dict-backed, for single-process mode and tests;
- :class:`SqliteTableRepo` — stdlib sqlite3 file DB for durable single-host
  deployments (crash recovery semantics, SURVEY.md section 5); a MySQL-backed
  implementation can slot in behind the same interface for cluster mode.

All values are stored as TEXT (the reference serializes JSON into MySQL text
columns the same way); typed access is the caller's concern.
"""

from __future__ import annotations

import abc
import sqlite3
import threading
from typing import Any, Dict, List, Optional, Sequence


class TableRepo(abc.ABC):
    """Narrow table interface shared by all control-plane state."""

    @abc.abstractmethod
    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        """Insert rows given a column->values mapping (reference
        ``SqlDataBase.add_item`` signature)."""

    @abc.abstractmethod
    def get_item_value(self, identify_name: str, identify_value: Any, item: str) -> Optional[Any]:
        """Value of column ``item`` for the first row where
        ``identify_name == identify_value``."""

    @abc.abstractmethod
    def set_item_value(self, identify_name: str, identify_value: Any, item: str, value: Any) -> bool:
        """Set column ``item`` on all rows matching the identifier."""

    @abc.abstractmethod
    def delete_items(self, **conditions: Any) -> bool:
        """Delete all rows matching the conditions."""

    @abc.abstractmethod
    def get_values_by_conditions(self, item: str, **conditions: Any) -> List[Any]:
        """All values of column ``item`` over rows matching the conditions."""

    @abc.abstractmethod
    def query_all(self) -> List[Dict[str, Any]]:
        """Every row as a dict."""

    # Convenience shared helpers -------------------------------------------------
    def has_item(self, identify_name: str, identify_value: Any) -> bool:
        return len(self.get_values_by_conditions(identify_name, **{identify_name: identify_value})) > 0


class MemoryTableRepo(TableRepo):
    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self._rows: List[Dict[str, Any]] = []
        self._lock = threading.RLock()

    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        with self._lock:
            lengths = {len(v) for v in item.values()}
            if len(lengths) > 1:
                return False
            n = lengths.pop() if lengths else 0
            for i in range(n):
                row = {c: None for c in self.columns}
                for k, vals in item.items():
                    if k not in self.columns:
                        return False
                    row[k] = vals[i]
                self._rows.append(row)
            return True

    def get_item_value(self, identify_name, identify_value, item):
        with self._lock:
            for row in self._rows:
                if row.get(identify_name) == identify_value:
                    return row.get(item)
            return None

    def set_item_value(self, identify_name, identify_value, item, value) -> bool:
        with self._lock:
            if item not in self.columns:
                return False
            hit = False
            for row in self._rows:
                if row.get(identify_name) == identify_value:
                    row[item] = value
                    hit = True
            return hit

    def delete_items(self, **conditions) -> bool:
        with self._lock:
            before = len(self._rows)
            self._rows = [
                r for r in self._rows
                if not all(r.get(k) == v for k, v in conditions.items())
            ]
            return len(self._rows) < before

    def get_values_by_conditions(self, item, **conditions):
        with self._lock:
            return [
                r.get(item) for r in self._rows
                if all(r.get(k) == v for k, v in conditions.items())
            ]

    def query_all(self):
        with self._lock:
            return [dict(r) for r in self._rows]


class SqliteTableRepo(TableRepo):
    """sqlite3-backed repo; one table per instance, TEXT columns.

    check_same_thread=False + a process lock gives the same
    many-threads/one-writer discipline the reference relies on (its services
    share one SqlDataBase handle across daemon threads).
    """

    def __init__(self, path: str, table: str, columns: Sequence[str]):
        if not table.isidentifier():
            raise ValueError(f"invalid table name {table!r}")
        for c in columns:
            if not c.isidentifier():
                raise ValueError(f"invalid column name {c!r}")
        self.table = table
        self.columns = list(columns)
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        cols = ", ".join(f"{c} TEXT" for c in self.columns)
        with self._lock:
            self._conn.execute(f"CREATE TABLE IF NOT EXISTS {table} ({cols})")
            self._conn.commit()

    def _col(self, name: str) -> str:
        if name not in self.columns:
            raise KeyError(f"unknown column {name!r} for table {self.table}")
        return name

    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        try:
            keys = [self._col(k) for k in item]
            lengths = {len(v) for v in item.values()}
            if len(lengths) > 1:
                return False
            n = lengths.pop() if lengths else 0
            placeholders = ", ".join("?" for _ in keys)
            sql = f"INSERT INTO {self.table} ({', '.join(keys)}) VALUES ({placeholders})"
            with self._lock:
                for i in range(n):
                    self._conn.execute(sql, [item[k][i] for k in keys])
                self._conn.commit()
            return True
        except (sqlite3.Error, KeyError):
            return False

    def get_item_value(self, identify_name, identify_value, item):
        sql = (
            f"SELECT {self._col(item)} FROM {self.table} "
            f"WHERE {self._col(identify_name)} = ? LIMIT 1"
        )
        with self._lock:
            cur = self._conn.execute(sql, (identify_value,))
            row = cur.fetchone()
        return row[0] if row else None

    def set_item_value(self, identify_name, identify_value, item, value) -> bool:
        try:
            sql = (
                f"UPDATE {self.table} SET {self._col(item)} = ? "
                f"WHERE {self._col(identify_name)} = ?"
            )
            with self._lock:
                cur = self._conn.execute(sql, (value, identify_value))
                self._conn.commit()
            return cur.rowcount > 0
        except sqlite3.Error:
            return False

    def delete_items(self, **conditions) -> bool:
        try:
            clause = " AND ".join(f"{self._col(k)} = ?" for k in conditions)
            sql = f"DELETE FROM {self.table}" + (f" WHERE {clause}" if clause else "")
            with self._lock:
                cur = self._conn.execute(sql, list(conditions.values()))
                self._conn.commit()
            return cur.rowcount > 0
        except sqlite3.Error:
            return False

    def get_values_by_conditions(self, item, **conditions):
        clause = " AND ".join(f"{self._col(k)} = ?" for k in conditions)
        sql = f"SELECT {self._col(item)} FROM {self.table}" + (
            f" WHERE {clause}" if clause else ""
        )
        with self._lock:
            cur = self._conn.execute(sql, list(conditions.values()))
            return [r[0] for r in cur.fetchall()]

    def query_all(self):
        with self._lock:
            cur = self._conn.execute(f"SELECT {', '.join(self.columns)} FROM {self.table}")
            rows = cur.fetchall()
        return [dict(zip(self.columns, r)) for r in rows]

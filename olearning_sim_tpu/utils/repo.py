"""State repositories.

The reference persists every piece of control-plane state in MySQL via a
generic table accessor (``ols_core/utils/repo_utils.py:19-400`` SqlDataBase,
specialized as TaskTableRepo / ResTableRepo / the deviceflow table). The
rebuild keeps the same narrow interface but behind an ABC with two default
implementations:

- :class:`MemoryTableRepo` — dict-backed, for single-process mode and tests;
- :class:`SqliteTableRepo` — stdlib sqlite3 file DB for durable single-host
  deployments (crash recovery semantics, SURVEY.md section 5);
- :class:`MySqlTableRepo` — the cluster-mode shared state bus the reference
  runs on (``repo_utils.py``'s ``mysql+pymysql`` engine), as a DBAPI
  adapter with the reference's reconnect-once-then-retry discipline.
  Import-gated: the driver module (pymysql) loads only on the production
  path; tests inject sqlite3 connections through the same adapter code.

All values are stored as TEXT (the reference serializes JSON into MySQL text
columns the same way); typed access is the caller's concern.
"""

from __future__ import annotations

import abc
import contextlib
import sqlite3
import threading
from typing import Any, Dict, List, Optional, Sequence


# "database is locked/busy" flavors sqlite raises when busy_timeout runs
# out under write contention. WAL + busy_timeout absorb most of it, but at
# hundreds of concurrent writers (submit-storm scale) the timeout itself
# can expire — those writes go through a bounded RetryPolicy instead of
# surfacing a transient as a hard failure.
_LOCKED_MARKERS = ("database is locked", "database is busy",
                   "database table is locked")
_LOCKED = object()  # sentinel: the attempt hit a locked error
_LOCKED_POLICY = None


def _locked_error(e: BaseException) -> bool:
    return isinstance(e, sqlite3.OperationalError) and any(
        m in str(e).lower() for m in _LOCKED_MARKERS
    )


def _locked_policy():
    global _LOCKED_POLICY
    if _LOCKED_POLICY is None:
        # Lazy import: resilience pulls in telemetry/numpy; the repo layer
        # must stay importable without them at module-import time.
        from olearning_sim_tpu.resilience.retry import RetryPolicy

        # retry_on=(): raised exceptions are NEVER absorbed here — only
        # the locked sentinel routed through the bool contract retries, so
        # a real error (missing table, corrupt file) surfaces immediately.
        _LOCKED_POLICY = RetryPolicy(max_attempts=6, base_delay=0.01,
                                     max_delay=0.25, jitter=0.25,
                                     retry_on=())
    return _LOCKED_POLICY


def retry_locked(fn, policy=None, point: str = "repo.sqlite_locked"):
    """Run ``fn`` under a bounded retry on sqlite lock contention.

    Only ``OperationalError: database is locked/busy`` is retried (and
    recorded as ``retry`` resilience events under ``point``); every other
    error propagates immediately. When the budget runs out the last locked
    error is re-raised — the caller's normal sqlite3.Error handling
    applies, so contracts (False/None returns) survive unchanged.
    """
    pol = policy if policy is not None else _locked_policy()
    last: List[BaseException] = []

    def attempt():
        try:
            return fn()
        except sqlite3.OperationalError as e:
            if not _locked_error(e):
                raise
            last.append(e)
            return _LOCKED

    result = pol.call(attempt, retry_if=lambda r: r is _LOCKED, point=point)
    if result is _LOCKED:
        raise last[-1]
    return result


def connect_sqlite(path: str, *, busy_timeout_s: float = 30.0,
                   synchronous: str = "NORMAL") -> sqlite3.Connection:
    """The one way the platform opens a sqlite control-plane DB.

    Every raw ``sqlite3.connect(..., check_same_thread=False)`` call site
    (task table, intake queue, durable deviceflow rooms) used to set its own
    pragmas — or none, so a supervisor thread writing while a gRPC thread
    read would hit ``database is locked``. This helper enables WAL (readers
    never block the writer and vice versa) and a busy timeout (a second
    writer waits instead of raising) for all of them.
    """
    conn = sqlite3.connect(path, check_same_thread=False,
                           timeout=busy_timeout_s)
    with contextlib.suppress(sqlite3.Error):
        # ":memory:" and some read-only mounts refuse WAL; the connection is
        # still usable, just without multi-process concurrency.
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute(f"PRAGMA synchronous={synchronous}")
    conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_s * 1000)}")
    return conn


class TableRepo(abc.ABC):
    """Narrow table interface shared by all control-plane state."""

    @abc.abstractmethod
    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        """Insert rows given a column->values mapping (reference
        ``SqlDataBase.add_item`` signature)."""

    @abc.abstractmethod
    def get_item_value(self, identify_name: str, identify_value: Any, item: str) -> Optional[Any]:
        """Value of column ``item`` for the first row where
        ``identify_name == identify_value``."""

    @abc.abstractmethod
    def set_item_value(self, identify_name: str, identify_value: Any, item: str, value: Any) -> bool:
        """Set column ``item`` on all rows matching the identifier."""

    @abc.abstractmethod
    def delete_items(self, **conditions: Any) -> bool:
        """Delete all rows matching the conditions."""

    @abc.abstractmethod
    def get_values_by_conditions(self, item: str, **conditions: Any) -> List[Any]:
        """All values of column ``item`` over rows matching the conditions."""

    @abc.abstractmethod
    def query_all(self) -> List[Dict[str, Any]]:
        """Every row as a dict."""

    # Convenience shared helpers -------------------------------------------------
    def has_item(self, identify_name: str, identify_value: Any) -> bool:
        return len(self.get_values_by_conditions(identify_name, **{identify_name: identify_value})) > 0

    @staticmethod
    def _lease_claimable(owner: Any, expires: Any, owner_value: str,
                         now: float, steal: bool) -> bool:
        """Shared claim predicate. With ``steal`` a row is claimable when it
        is already ours, unowned, or its lease has expired (a set owner with
        no parseable expiry is a legacy/torn row — treated as expired).
        Without ``steal`` (renewal) ONLY the current owner qualifies — a
        renewal that succeeded on an unowned row would let a fenced/stale
        process silently re-adopt a task that was already finalized."""
        if owner == owner_value:
            return True
        if not steal:
            return False
        if owner in (None, ""):
            return True
        try:
            return float(expires) < now
        except (TypeError, ValueError):
            return True

    def claim_row(self, identify_name: str, identify_value: Any,
                  owner_item: str, owner_value: str, expires_item: str,
                  new_expires: float, now: float, steal: bool = True) -> bool:
        """Atomic conditional ownership write (the lease CAS): set
        ``owner_item = owner_value`` and ``expires_item = new_expires`` iff
        the row is currently unowned, already owned by ``owner_value``, or
        (when ``steal``) its lease expired before ``now``. Returns True iff
        this caller owns the row after the call.

        This base implementation is read-check-write and therefore only
        best-effort for exotic backends; :class:`MemoryTableRepo` (process
        lock), :class:`SqliteTableRepo`, and :class:`MySqlTableRepo`
        (single conditional UPDATE) override it with genuinely atomic
        versions.
        """
        owner = self.get_item_value(identify_name, identify_value, owner_item)
        expires = self.get_item_value(identify_name, identify_value, expires_item)
        if not self._lease_claimable(owner, expires, owner_value, now, steal):
            return False
        ok = self.set_item_value(identify_name, identify_value, owner_item,
                                 owner_value)
        if not ok:
            return False
        self.set_item_value(identify_name, identify_value, expires_item,
                            repr(float(new_expires)))
        return True

    def release_row(self, identify_name: str, identify_value: Any,
                    owner_item: str, owner_value: str,
                    expires_item: str) -> bool:
        """Conditionally drop ownership: clear ``owner_item`` and
        ``expires_item`` iff ``owner_item == owner_value``. Like claim_row,
        the base version is read-check-write; the concrete backends make it
        a single atomic conditional UPDATE so a release racing a steal can
        never wipe the new owner's live lease."""
        owner = self.get_item_value(identify_name, identify_value, owner_item)
        if owner != owner_value:
            return False
        self.set_item_value(identify_name, identify_value, owner_item, "")
        self.set_item_value(identify_name, identify_value, expires_item, "")
        return True


class MemoryTableRepo(TableRepo):
    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self._rows: List[Dict[str, Any]] = []
        self._lock = threading.RLock()

    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        with self._lock:
            lengths = {len(v) for v in item.values()}
            if len(lengths) > 1:
                return False
            n = lengths.pop() if lengths else 0
            for i in range(n):
                row = {c: None for c in self.columns}
                for k, vals in item.items():
                    if k not in self.columns:
                        return False
                    row[k] = vals[i]
                self._rows.append(row)
            return True

    def get_item_value(self, identify_name, identify_value, item):
        with self._lock:
            for row in self._rows:
                if row.get(identify_name) == identify_value:
                    return row.get(item)
            return None

    def set_item_value(self, identify_name, identify_value, item, value) -> bool:
        with self._lock:
            if item not in self.columns:
                return False
            hit = False
            for row in self._rows:
                if row.get(identify_name) == identify_value:
                    row[item] = value
                    hit = True
            return hit

    def delete_items(self, **conditions) -> bool:
        with self._lock:
            before = len(self._rows)
            self._rows = [
                r for r in self._rows
                if not all(r.get(k) == v for k, v in conditions.items())
            ]
            return len(self._rows) < before

    def get_values_by_conditions(self, item, **conditions):
        with self._lock:
            return [
                r.get(item) for r in self._rows
                if all(r.get(k) == v for k, v in conditions.items())
            ]

    def query_all(self):
        with self._lock:
            return [dict(r) for r in self._rows]

    def claim_row(self, identify_name, identify_value, owner_item,
                  owner_value, expires_item, new_expires, now,
                  steal: bool = True) -> bool:
        with self._lock:
            for row in self._rows:
                if row.get(identify_name) != identify_value:
                    continue
                if not self._lease_claimable(
                    row.get(owner_item), row.get(expires_item),
                    owner_value, now, steal,
                ):
                    return False
                row[owner_item] = owner_value
                row[expires_item] = repr(float(new_expires))
                return True
            return False

    def release_row(self, identify_name, identify_value, owner_item,
                    owner_value, expires_item) -> bool:
        with self._lock:
            for row in self._rows:
                if row.get(identify_name) != identify_value:
                    continue
                if row.get(owner_item) != owner_value:
                    return False
                row[owner_item] = ""
                row[expires_item] = ""
                return True
            return False


class SqliteTableRepo(TableRepo):
    """sqlite3-backed repo; one table per instance, TEXT columns.

    check_same_thread=False + a process lock gives the same
    many-threads/one-writer discipline the reference relies on (its services
    share one SqlDataBase handle across daemon threads).
    """

    def __init__(self, path: str, table: str, columns: Sequence[str]):
        if not table.isidentifier():
            raise ValueError(f"invalid table name {table!r}")
        for c in columns:
            if not c.isidentifier():
                raise ValueError(f"invalid column name {c!r}")
        self.table = table
        self.columns = list(columns)
        self._lock = threading.RLock()
        self._conn = connect_sqlite(path)
        cols = ", ".join(f"{c} TEXT" for c in self.columns)
        with self._lock:
            self._conn.execute(f"CREATE TABLE IF NOT EXISTS {table} ({cols})")
            # Schema evolution: a DB file created by an older build may lack
            # columns added since (e.g. "resilience"); CREATE IF NOT EXISTS
            # keeps the old table, so add any missing ones in place.
            existing = {
                row[1] for row in
                self._conn.execute(f"PRAGMA table_info({table})")
            }
            for c in self.columns:
                if c not in existing:
                    self._conn.execute(
                        f"ALTER TABLE {table} ADD COLUMN {c} TEXT"
                    )
            self._conn.commit()

    def _col(self, name: str) -> str:
        if name not in self.columns:
            raise KeyError(f"unknown column {name!r} for table {self.table}")
        return name

    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        try:
            keys = [self._col(k) for k in item]
            lengths = {len(v) for v in item.values()}
            if len(lengths) > 1:
                return False
            n = lengths.pop() if lengths else 0
            placeholders = ", ".join("?" for _ in keys)
            sql = f"INSERT INTO {self.table} ({', '.join(keys)}) VALUES ({placeholders})"
            with self._lock:
                for i in range(n):
                    self._conn.execute(sql, [item[k][i] for k in keys])
                self._conn.commit()
            return True
        except (sqlite3.Error, KeyError):
            return False

    def get_item_value(self, identify_name, identify_value, item):
        sql = (
            f"SELECT {self._col(item)} FROM {self.table} "
            f"WHERE {self._col(identify_name)} = ? LIMIT 1"
        )
        with self._lock:
            cur = self._conn.execute(sql, (identify_value,))
            row = cur.fetchone()
        return row[0] if row else None

    def set_item_value(self, identify_name, identify_value, item, value) -> bool:
        try:
            sql = (
                f"UPDATE {self.table} SET {self._col(item)} = ? "
                f"WHERE {self._col(identify_name)} = ?"
            )

            def op():
                with self._lock:
                    cur = self._conn.execute(sql, (value, identify_value))
                    self._conn.commit()
                return cur.rowcount > 0

            return retry_locked(op)
        except sqlite3.Error:
            return False

    def delete_items(self, **conditions) -> bool:
        try:
            clause = " AND ".join(f"{self._col(k)} = ?" for k in conditions)
            sql = f"DELETE FROM {self.table}" + (f" WHERE {clause}" if clause else "")
            with self._lock:
                cur = self._conn.execute(sql, list(conditions.values()))
                self._conn.commit()
            return cur.rowcount > 0
        except sqlite3.Error:
            return False

    def get_values_by_conditions(self, item, **conditions):
        clause = " AND ".join(f"{self._col(k)} = ?" for k in conditions)
        sql = f"SELECT {self._col(item)} FROM {self.table}" + (
            f" WHERE {clause}" if clause else ""
        )
        with self._lock:
            cur = self._conn.execute(sql, list(conditions.values()))
            return [r[0] for r in cur.fetchall()]

    def query_all(self):
        with self._lock:
            cur = self._conn.execute(f"SELECT {', '.join(self.columns)} FROM {self.table}")
            rows = cur.fetchall()
        return [dict(zip(self.columns, r)) for r in rows]

    def _claim_sql(self, identify_name: str, owner_item: str,
                   expires_item: str, steal: bool, ph: str = "?") -> str:
        """One conditional UPDATE = the whole CAS: the WHERE clause encodes
        the claim predicate (renewal: current owner ONLY; steal: owner, or
        unowned, or expired/torn lease), so two processes racing on the
        same file DB cannot both win (sqlite serializes writers; rowcount
        arbitrates)."""
        cond = f"({owner_item} = {ph}"
        if steal:
            cond += (f" OR {owner_item} IS NULL OR {owner_item} = ''"
                     f" OR {expires_item} IS NULL OR {expires_item} = ''"
                     f" OR CAST({expires_item} AS REAL) < {ph}")
        cond += ")"
        return (f"UPDATE {self.table} SET {owner_item} = {ph}, "
                f"{expires_item} = {ph} WHERE {identify_name} = {ph} AND {cond}")

    def claim_row(self, identify_name, identify_value, owner_item,
                  owner_value, expires_item, new_expires, now,
                  steal: bool = True) -> bool:
        try:
            sql = self._claim_sql(self._col(identify_name),
                                  self._col(owner_item),
                                  self._col(expires_item), steal)
            params = [owner_value, repr(float(new_expires)), identify_value,
                      owner_value]
            if steal:
                params.append(float(now))

            # The lease CAS under storm concurrency: a locked error here is
            # NOT an arbitration loss (the UPDATE never ran) — retry it
            # bounded instead of reading it as "claim refused".
            def op():
                with self._lock:
                    cur = self._conn.execute(sql, params)
                    self._conn.commit()
                return cur.rowcount > 0

            return retry_locked(op)
        except sqlite3.Error:
            return False

    def release_row(self, identify_name, identify_value, owner_item,
                    owner_value, expires_item) -> bool:
        try:
            sql = (f"UPDATE {self.table} SET {self._col(owner_item)} = '', "
                   f"{self._col(expires_item)} = '' WHERE "
                   f"{self._col(identify_name)} = ? AND "
                   f"{self._col(owner_item)} = ?")

            def op():
                with self._lock:
                    cur = self._conn.execute(sql, (identify_value, owner_value))
                    self._conn.commit()
                return cur.rowcount > 0

            return retry_locked(op)
        except sqlite3.Error:
            return False


class MySqlTableRepo(TableRepo):
    """MySQL-backed repo over any DBAPI-2.0 connection.

    The reference's shared control-plane bus is MySQL behind SQLAlchemy
    (``ols_core/utils/repo_utils.py:31-36`` builds a ``mysql+pymysql``
    engine; every accessor catches OperationalError, re-initializes the
    connection ONCE, and retries — ``:49-56``, ``:89-104``). This adapter
    keeps that exact discipline over a plain DBAPI driver (no SQLAlchemy
    in this image): ``connect`` is a zero-arg factory returning a fresh
    connection, every operation retries once through a fresh connection on
    failure, and errors degrade to False/None/[] rather than raising (the
    reference's posture — callers poll).

    ``paramstyle``: "format" for pymysql/mysql-connector (%s), "qmark"
    for DBAPI drivers like sqlite3 (?) — which is also how the adapter's
    SQL generation and retry logic stay testable without a MySQL server.
    """

    def __init__(self, connect, table: str, columns: Sequence[str],
                 paramstyle: str = "format"):
        if not table.isidentifier():
            raise ValueError(f"invalid table name {table!r}")
        for c in columns:
            if not c.isidentifier():
                raise ValueError(f"invalid column name {c!r}")
        if paramstyle not in ("format", "qmark"):
            raise ValueError(f"unsupported paramstyle {paramstyle!r}")
        self.table = table
        self.columns = list(columns)
        self._connect = connect
        self._ph = "%s" if paramstyle == "format" else "?"
        self._lock = threading.RLock()
        self._conn = connect()

    @classmethod
    def from_config(cls, host: str, port: int, user: str, password: str,
                    database: str, table: str, columns: Sequence[str]):
        """Production constructor (reference ``SqlDataBase.__init__`` reads
        the same fields from table YAMLs, ``repo_utils.py:20-29``).
        Import-gated on pymysql."""
        import pymysql  # noqa: PLC0415 — only the MySQL path needs it

        def connect():
            return pymysql.connect(host=host, port=int(port), user=user,
                                   password=password, database=database,
                                   autocommit=False)

        return cls(connect, table, columns, paramstyle="format")

    def _col(self, name: str) -> str:
        if name not in self.columns:
            raise KeyError(f"unknown column {name!r} for table {self.table}")
        return name

    def _execute(self, sql: str, params: Sequence[Any]):
        """Run one statement; on ANY connection/driver error, reconnect once
        and retry (reference ``:49-56``). Raises only if the retry fails too
        — callers translate that into their False/None returns."""
        cur = self._execute_batch(sql, [tuple(params)])
        return cur

    def _execute_batch(self, sql: str, rows: Sequence[Sequence[Any]]):
        """Run one statement over many param rows in a SINGLE transaction
        (all rows, then one commit — same all-or-nothing semantics as
        SqliteTableRepo's add_item). On failure: roll back, reconnect once,
        retry the WHOLE batch; a second failure rolls back and raises, so a
        partial prefix is never left committed for the caller to re-insert."""
        with self._lock:
            for attempt in (0, 1):
                try:
                    cur = self._conn.cursor()
                    for row in rows:
                        cur.execute(sql, tuple(row))
                    self._conn.commit()
                    return cur
                except Exception:  # noqa: BLE001 — DBAPI error bases vary by driver
                    try:
                        self._conn.rollback()
                    except Exception:  # lint: allow-silent — cleanup of a
                        pass           # failed conn; original error re-raised
                    if attempt:
                        raise
                    try:
                        self._conn.close()
                    except Exception:  # lint: allow-silent — closing the
                        pass           # dead conn before the reconnect retry
                    self._conn = self._connect()

    def add_item(self, item: Dict[str, List[Any]]) -> bool:
        try:
            keys = [self._col(k) for k in item]
            lengths = {len(v) for v in item.values()}
            if len(lengths) > 1:
                return False
            n = lengths.pop() if lengths else 0
            placeholders = ", ".join(self._ph for _ in keys)
            sql = (f"INSERT INTO {self.table} ({', '.join(keys)}) "
                   f"VALUES ({placeholders})")
            self._execute_batch(sql, [[item[k][i] for k in keys]
                                      for i in range(n)])
            return True
        except Exception:  # noqa: BLE001
            return False

    def get_item_value(self, identify_name, identify_value, item):
        try:
            sql = (f"SELECT {self._col(item)} FROM {self.table} "
                   f"WHERE {self._col(identify_name)} = {self._ph} LIMIT 1")
            row = self._execute(sql, (identify_value,)).fetchone()
            return row[0] if row else None
        except Exception:  # noqa: BLE001
            return None

    def set_item_value(self, identify_name, identify_value, item, value) -> bool:
        try:
            sql = (f"UPDATE {self.table} SET {self._col(item)} = {self._ph} "
                   f"WHERE {self._col(identify_name)} = {self._ph}")
            return self._execute(sql, (value, identify_value)).rowcount > 0
        except Exception:  # noqa: BLE001
            return False

    def delete_items(self, **conditions) -> bool:
        try:
            clause = " AND ".join(
                f"{self._col(k)} = {self._ph}" for k in conditions
            )
            sql = f"DELETE FROM {self.table}" + (
                f" WHERE {clause}" if clause else ""
            )
            return self._execute(sql, list(conditions.values())).rowcount > 0
        except Exception:  # noqa: BLE001
            return False

    def get_values_by_conditions(self, item, **conditions):
        try:
            clause = " AND ".join(
                f"{self._col(k)} = {self._ph}" for k in conditions
            )
            sql = f"SELECT {self._col(item)} FROM {self.table}" + (
                f" WHERE {clause}" if clause else ""
            )
            return [r[0] for r in self._execute(
                sql, list(conditions.values())).fetchall()]
        except Exception:  # noqa: BLE001
            return []

    def query_all(self):
        try:
            cur = self._execute(
                f"SELECT {', '.join(self.columns)} FROM {self.table}", ()
            )
            return [dict(zip(self.columns, r)) for r in cur.fetchall()]
        except Exception:  # noqa: BLE001
            return []

    def claim_row(self, identify_name, identify_value, owner_item,
                  owner_value, expires_item, new_expires, now,
                  steal: bool = True) -> bool:
        """Single conditional UPDATE (see SqliteTableRepo._claim_sql); the
        DB serializes concurrent claimers and rowcount arbitrates.
        DECIMAL cast: valid in MySQL and mapped to NUMERIC affinity by the
        sqlite driver the adapter is tested against."""
        try:
            oi, ei = self._col(owner_item), self._col(expires_item)
            cond = f"({oi} = {self._ph}"
            if steal:
                cond += (f" OR {oi} IS NULL OR {oi} = ''"
                         f" OR {ei} IS NULL OR {ei} = ''"
                         f" OR CAST({ei} AS DECIMAL(20,6)) < {self._ph}")
            cond += ")"
            sql = (f"UPDATE {self.table} SET {oi} = {self._ph}, "
                   f"{ei} = {self._ph} WHERE "
                   f"{self._col(identify_name)} = {self._ph} AND {cond}")
            params = [owner_value, repr(float(new_expires)), identify_value,
                      owner_value]
            if steal:
                params.append(float(now))
            return self._execute(sql, params).rowcount > 0
        except Exception:  # noqa: BLE001
            return False

    def release_row(self, identify_name, identify_value, owner_item,
                    owner_value, expires_item) -> bool:
        try:
            sql = (f"UPDATE {self.table} SET {self._col(owner_item)} = '', "
                   f"{self._col(expires_item)} = '' WHERE "
                   f"{self._col(identify_name)} = {self._ph} AND "
                   f"{self._col(owner_item)} = {self._ph}")
            return self._execute(
                sql, (identify_value, owner_value)
            ).rowcount > 0
        except Exception:  # noqa: BLE001
            return False

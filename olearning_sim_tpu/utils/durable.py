"""Crash-consistent file commit helpers.

The platform's durable artifacts (staged file-repo uploads, checkpoint
manifests, supervision records) all follow the same commit discipline:
write to a unique temp file in the destination directory, fsync the data,
``os.replace`` onto the final name (atomic within one filesystem), then
fsync the parent directory so the rename itself survives a host crash.
``os.replace`` without the surrounding fsyncs only protects against
*process* death — after a power cut or kernel panic the filesystem may
replay the rename but not the data, "committing" a zero-length or torn
file. These helpers are that discipline, written once.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-committed rename inside it is durable.
    Best-effort: some filesystems (and all of Windows) refuse directory
    fds — an environment limitation, not a caller error."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        with contextlib.suppress(OSError):
            os.fsync(fd)
    finally:
        os.close(fd)


def copy_file_durable(src: str, tmp: str) -> None:
    """Copy ``src`` into the (already created) staging path ``tmp`` and
    fsync the data before returning — the pre-rename half of a durable
    stage-then-rename."""
    with open(src, "rb") as fin, open(tmp, "wb") as fout:
        shutil.copyfileobj(fin, fout)
        fout.flush()
        os.fsync(fout.fileno())


def commit_replace(tmp: str, dest: str) -> None:
    """The commit point: atomically rename the fsynced staging file onto
    ``dest`` and fsync the parent directory."""
    os.replace(tmp, dest)
    fsync_dir(os.path.dirname(dest) or ".")


def atomic_write_bytes(dest: str, data: bytes) -> None:
    """Write ``data`` to ``dest`` with full tmp -> fsync -> replace ->
    fsync(dir) crash consistency. A reader never observes a partial file;
    after return the content survives a host crash."""
    directory = os.path.dirname(dest) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(dest) + ".", dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        commit_replace(tmp, dest)
    finally:
        if os.path.exists(tmp):
            with contextlib.suppress(OSError):
                os.remove(tmp)

"""JAX version compatibility shims.

The codebase targets the current ``jax.shard_map`` API (top-level export,
``axis_names=`` manual-axes selection, varying-manual-axes typing via
``jax.lax.pvary``). Older runtimes (<= 0.4.x) ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the complementary ``auto=``
parameter and no VMA typing. Rather than gate every call site, the package
installs a thin adapter at import time when (and only when) the running jax
lacks the modern surface — one robustness layer instead of N sprinkled
version checks.
"""

from __future__ import annotations

import functools


def ensure_jax_compat() -> None:
    """Install ``jax.shard_map`` on runtimes that predate the top-level API.

    Semantics mapping: ``axis_names={a, ...}`` (axes manual in the body)
    becomes ``auto = mesh.axis_names - axis_names``; replication checking is
    disabled because pre-VMA runtimes cannot type device-varying carries
    (``jax.lax.pvary`` does not exist there — see ``fedcore._to_varying``,
    which degrades to identity for the same reason).
    """
    import jax

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(_legacy_shard_map)
    def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, **kwargs):
        auto = kwargs.pop("auto", None)
        if auto is None:
            auto = frozenset()
            if axis_names is not None:
                auto = frozenset(getattr(mesh, "axis_names", ())) - frozenset(
                    axis_names
                )
        if f is None:
            # Decorator form: jax.shard_map(mesh=..., ...)(f)
            return lambda fn: shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                axis_names=axis_names, auto=auto,
            )
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False, auto=frozenset(auto),
        )

    jax.shard_map = shard_map

"""Monotonic-clock helpers: the one place timeout arithmetic lives.

Every timeout/deadline in the platform must be measured with
``time.monotonic()``, never ``time.time()``: wall-clock steps (NTP
correction, manual clock set, VM migration) move ``time.time()`` by
arbitrary amounts in either direction, which makes a wall-clock-based
barrier either expire instantly (forward step) or stall far past its
timeout (backward step). ``time.monotonic()`` is immune by contract.

Call sites should use :class:`Deadline` (stateful countdown) or
:func:`monotonic` (raw now) from here rather than importing ``time``
directly for timeout math — one helper, one clock, one place to audit.
"""

from __future__ import annotations

import time


def monotonic() -> float:
    """The platform timeout clock (``time.monotonic``)."""
    return time.monotonic()


class Deadline:
    """A countdown measured on the monotonic clock.

    ``Deadline(5.0)`` expires 5 seconds of *monotonic* time from
    construction, regardless of what the wall clock does in between.
    ``timeout_s=None`` never expires (an explicit "no deadline").
    """

    __slots__ = ("timeout_s", "_t0")

    def __init__(self, timeout_s: float | None):
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self._t0 = monotonic()

    def elapsed(self) -> float:
        return monotonic() - self._t0

    def remaining(self) -> float:
        if self.timeout_s is None:
            return float("inf")
        return self.timeout_s - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

"""Dual-sink structured logger.

Reference: ``ols_core/simu_log.py:13-186`` — every component logs
``(task_id, system_name, module_name, message, log_type)`` to both a rotating
local file and a MySQL ``log_table``. Here the second sink is any
:class:`~olearning_sim_tpu.utils.repo.TableRepo` (sqlite/in-memory/whatever),
so single-process mode needs no database.
"""

from __future__ import annotations

import datetime
import logging
import logging.handlers
import os
import threading
from typing import Optional

from olearning_sim_tpu.utils.repo import TableRepo

LOG_COLUMNS = ["time", "task_id", "system_name", "module_name", "message", "log_type"]


class Logger:
    """``Logger().info(task_id=..., system_name=..., module_name=..., message=...)``

    contract preserved from the reference so call sites read identically.
    """

    _file_loggers = {}
    _file_lock = threading.Lock()

    def __init__(
        self,
        log_path: Optional[str] = None,
        repo: Optional[TableRepo] = None,
        name: str = "olearning_sim_tpu",
        stderr: bool = False,
    ):
        self.repo = repo
        self._logger = logging.getLogger(name)
        self._logger.setLevel(logging.INFO)
        if log_path:
            with Logger._file_lock:
                if log_path not in Logger._file_loggers:
                    os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
                    handler = logging.handlers.RotatingFileHandler(
                        log_path, maxBytes=50 * 1024 * 1024, backupCount=5
                    )
                    handler.setFormatter(logging.Formatter("%(asctime)s %(message)s"))
                    self._logger.addHandler(handler)
                    Logger._file_loggers[log_path] = handler
        if stderr and not any(
            isinstance(h, logging.StreamHandler) for h in self._logger.handlers
        ):
            self._logger.addHandler(logging.StreamHandler())

    def _log(self, level: str, task_id: str, system_name: str, module_name: str, message: str):
        line = f"[{level}][{system_name}][{module_name}][task={task_id}] {message}"
        getattr(self._logger, "warning" if level == "WARNING" else level.lower(), self._logger.info)(line)
        if self.repo is not None:
            self.repo.add_item(
                {
                    "time": [datetime.datetime.now().isoformat(timespec="seconds")],
                    "task_id": [task_id],
                    "system_name": [system_name],
                    "module_name": [module_name],
                    "message": [message],
                    "log_type": [level],
                }
            )

    def info(self, task_id: str = "", system_name: str = "", module_name: str = "", message: str = ""):
        self._log("INFO", task_id, system_name, module_name, message)

    def warning(self, task_id: str = "", system_name: str = "", module_name: str = "", message: str = ""):
        self._log("WARNING", task_id, system_name, module_name, message)

    def error(self, task_id: str = "", system_name: str = "", module_name: str = "", message: str = ""):
        self._log("ERROR", task_id, system_name, module_name, message)

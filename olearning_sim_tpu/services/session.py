"""SimulatorSession — one gRPC server hosting the selected control-plane
services (reference ``ols_core/simu_session.py:25-70``: boots
TaskMgr/ResourceMgr/RayClusterMgr/PerformanceMgr into one process by ``svc``
selector; here DeviceFlow and PhoneManager join the same process too, since
no external Pulsar/phone-farm processes are required in single-host mode).
"""

from __future__ import annotations

from concurrent import futures
from typing import Iterable, Optional, Tuple

import grpc

from olearning_sim_tpu.services.grpc_services import (
    DeviceFlowServicer,
    PerformanceMgrServicer,
    PhoneManagerServicer,
    ResourceMgrServicer,
    SliceMgrServicer,
    add_service_to_server,
)

ALL_SERVICES = ("taskmgr", "resourcemgr", "deviceflow", "phonemgr",
                "slicemgr", "performancemgr")


class SimulatorSession:
    """Compose managers into one served process.

    Any manager may be None (service omitted) — matching the reference's
    ``svc`` list selector. Construction wires defaults so
    ``SimulatorSession().start()`` gives a fully working single-host platform:
    ResourceManager over the local device topology, DeviceFlowService,
    PerformanceManager, ClusterManager, and a TaskManager wired to all of
    them (plus an optional SimulatedPhoneFarm).
    """

    def __init__(
        self,
        services: Iterable[str] = ALL_SERVICES,
        address: str = "127.0.0.1:0",
        task_manager=None,
        resource_manager=None,
        deviceflow=None,
        phone_farm=None,
        cluster_manager=None,
        performance_manager=None,
        max_workers: int = 16,
        metrics_port: Optional[int] = None,
        supervisor=None,
        supervise: bool = True,
    ):
        """``metrics_port`` — when set, start() also serves the telemetry
        registry on ``127.0.0.1:<metrics_port>`` (``/metrics`` Prometheus
        text, ``/metrics.json`` snapshot; 0 binds an ephemeral port,
        readable from ``session.metrics_server.port``).

        ``supervisor`` / ``supervise`` — crash-safe task supervision
        (docs/resilience.md): when the session hosts a task manager and
        ``supervise`` is on, a :class:`~olearning_sim_tpu.supervisor.
        TaskSupervisor` (the given one, or a default over the manager)
        starts/stops with the session, and a session-built manager recovers
        resume-first (orphaned RUNNING rows are left for the supervisor to
        reclaim instead of being failed on boot)."""
        self.services = tuple(services)
        self.address = address
        self._server: Optional[grpc.Server] = None
        self.port: Optional[int] = None
        self.metrics_port = metrics_port
        self.metrics_server = None

        if "resourcemgr" in self.services and resource_manager is None:
            from olearning_sim_tpu.resourcemgr.resource_manager import ResourceManager

            phone_provider = (
                phone_farm.get_device_available_resource
                if phone_farm is not None else None
            )
            resource_manager = ResourceManager(phone_provider=phone_provider)
        if "deviceflow" in self.services and deviceflow is None:
            from olearning_sim_tpu.deviceflow.service import DeviceFlowService

            deviceflow = DeviceFlowService()
        if "slicemgr" in self.services and cluster_manager is None:
            from olearning_sim_tpu.clustermgr import ClusterManager

            cluster_manager = ClusterManager()
        if "performancemgr" in self.services and performance_manager is None:
            from olearning_sim_tpu.performancemgr import PerformanceManager

            performance_manager = PerformanceManager()
        if "taskmgr" in self.services and task_manager is None:
            from olearning_sim_tpu.taskmgr.task_manager import TaskManager

            task_manager = TaskManager(
                resource_manager=resource_manager,
                deviceflow=deviceflow,
                phone_client=phone_farm,
                perf=performance_manager,
                supervise_orphans=supervise,
            )
        if (supervise and "taskmgr" in self.services
                and task_manager is not None):
            # A user-supplied manager must share the session's resume-first
            # posture, or its release loop would MISSING-fail orphans ahead
            # of the supervisor's reclaim. (Boot-time `_recover` already ran
            # at THAT manager's construction — managers built for a
            # supervised session should pass supervise_orphans=True
            # themselves to also recover resume-first.)
            task_manager._supervise_orphans = True
            if supervisor is None:
                from olearning_sim_tpu.supervisor import TaskSupervisor

                supervisor = TaskSupervisor(task_manager)
        self.supervisor = supervisor

        self.task_manager = task_manager
        self.resource_manager = resource_manager
        self.deviceflow = deviceflow
        self.phone_farm = phone_farm
        self.cluster_manager = cluster_manager
        self.performance_manager = performance_manager
        self._max_workers = max_workers

    # ------------------------------------------------------------------ boot
    def start(self) -> Tuple[grpc.Server, int]:
        server = grpc.server(futures.ThreadPoolExecutor(self._max_workers))
        if "taskmgr" in self.services and self.task_manager is not None:
            from olearning_sim_tpu.taskmgr.grpc_service import (
                TaskMgrServicer,
                add_taskmgr_to_server,
            )

            add_taskmgr_to_server(TaskMgrServicer(self.task_manager), server)
            self.task_manager.start()
            if self.supervisor is not None:
                self.supervisor.start()
        if "resourcemgr" in self.services and self.resource_manager is not None:
            add_service_to_server(ResourceMgrServicer(self.resource_manager), server)
        if "deviceflow" in self.services and self.deviceflow is not None:
            add_service_to_server(DeviceFlowServicer(self.deviceflow), server)
            self.deviceflow.start()
        if "phonemgr" in self.services and self.phone_farm is not None:
            add_service_to_server(PhoneManagerServicer(self.phone_farm), server)
        if "slicemgr" in self.services and self.cluster_manager is not None:
            add_service_to_server(SliceMgrServicer(self.cluster_manager), server)
        if "performancemgr" in self.services and self.performance_manager is not None:
            add_service_to_server(
                PerformanceMgrServicer(self.performance_manager), server
            )
        self.port = server.add_insecure_port(self.address)
        server.start()
        self._server = server
        if self.metrics_port is not None and self.metrics_server is None:
            from olearning_sim_tpu.telemetry import MetricsHTTPServer

            registry = getattr(self.performance_manager, "registry", None)
            self.metrics_server = MetricsHTTPServer(
                registry=registry, port=self.metrics_port
            ).start()
        return server, self.port

    def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.task_manager is not None and hasattr(self.task_manager, "stop"):
            self.task_manager.stop()
        if self.deviceflow is not None and hasattr(self.deviceflow, "stop"):
            self.deviceflow.stop()

    def __enter__(self) -> "SimulatorSession":
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

"""gRPC servicers + clients for ResourceMgr / DeviceFlow / PhoneManager /
SliceMgr / PerformanceMgr.

Same generic-handler pattern as ``taskmgr/grpc_service.py`` (the image ships
protoc without grpc_python_plugin). Each servicer is a thin adapter from the
wire surface (``proto/services.proto``, mirroring the reference's service
inventory) onto the corresponding in-process manager.
"""

from __future__ import annotations

import json
from typing import Dict, Tuple, Type

import grpc
from google.protobuf import empty_pb2

from olearning_sim_tpu.proto import services_pb2 as spb
from olearning_sim_tpu.proto import telemetry_pb2 as tpb


def _methods_of(service_cls) -> Dict[str, Tuple[Type, Type]]:
    return service_cls.METHODS


def add_service_to_server(servicer, server: grpc.Server) -> None:
    """Register any servicer class that defines SERVICE_NAME + METHODS."""
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        for name, (req, resp) in _methods_of(type(servicer)).items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(type(servicer).SERVICE_NAME, handlers),)
    )


class _ClientBase:
    SERVICE: Type = None

    def __init__(self, channel: grpc.Channel):
        self._calls = {
            name: channel.unary_unary(
                f"/{self.SERVICE.SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            for name, (req, resp) in _methods_of(self.SERVICE).items()
        }


def _phones_to_proto(phones: Dict[str, Dict[str, int]]):
    return [
        spb.UserPhoneResource(
            user_id=user,
            phones=[spb.PhoneTypeCount(phone_type=t, num=n)
                    for t, n in sorted(types.items())],
        )
        for user, types in sorted(phones.items())
    ]


def _phones_from_proto(users) -> Dict[str, Dict[str, int]]:
    return {u.user_id: {p.phone_type: p.num for p in u.phones} for u in users}


# ---------------------------------------------------------------- ResourceMgr
class ResourceMgrServicer:
    """Adapter onto :class:`ResourceManager` (resource ledger)."""

    SERVICE_NAME = "olearning_sim_tpu.services.ResourceMgr"
    METHODS = {
        "getResource": (empty_pb2.Empty, spb.ResourceSnapshot),
        "getClusterAvailableResource": (empty_pb2.Empty, spb.ClusterResource),
        "getClusterTotalResource": (empty_pb2.Empty, spb.ClusterResource),
        "getClusterResourceDetail": (empty_pb2.Empty, spb.ClusterDetail),
        "requestClusterResource": (spb.ClusterResourceRequest, spb.Ack),
        "releaseClusterResource": (spb.TaskRef, spb.Ack),
        "requestPhoneResource": (spb.PhoneResourceRequest, spb.Ack),
        "releaseResource": (spb.TaskRef, spb.Ack),
    }

    def __init__(self, manager):
        self.manager = manager

    def getResource(self, request, context) -> spb.ResourceSnapshot:
        res = self.manager.get_resource()
        return spb.ResourceSnapshot(
            logical_simulation=spb.ClusterResource(
                cpu=res["logical_simulation"]["cpu"],
                mem=res["logical_simulation"]["mem"],
            ),
            device_simulation=_phones_to_proto(res.get("device_simulation", {})),
            topology_json=json.dumps(res.get("topology", {})),
        )

    def getClusterAvailableResource(self, request, context) -> spb.ClusterResource:
        avail = self.manager.get_cluster_available_resource()
        return spb.ClusterResource(cpu=avail["cpu"], mem=avail["mem"])

    def getClusterTotalResource(self, request, context) -> spb.ClusterResource:
        total = self.manager.get_cluster_total_resource()
        return spb.ClusterResource(cpu=total["cpu"], mem=total["mem"])

    def getClusterResourceDetail(self, request, context) -> spb.ClusterDetail:
        return spb.ClusterDetail(
            detail_json=json.dumps(self.manager.get_cluster_resource_detail())
        )

    def requestClusterResource(self, request, context) -> spb.Ack:
        ok = self.manager.request_cluster_resource(
            request.task_id, request.user_id, request.cpu, request.mem
        )
        return spb.Ack(is_success=ok)

    def releaseClusterResource(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.manager.release_cluster_resource(request.task_id))

    def requestPhoneResource(self, request, context) -> spb.Ack:
        ok = self.manager.request_phone_resource(
            request.task_id, request.user_id,
            {p.phone_type: p.num for p in request.phones},
        )
        return spb.Ack(is_success=ok)

    def releaseResource(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.manager.release_resource(request.task_id))


class ResourceMgrClient(_ClientBase):
    SERVICE = ResourceMgrServicer

    def get_resource(self):
        snap = self._calls["getResource"](empty_pb2.Empty())
        return {
            "logical_simulation": {"cpu": snap.logical_simulation.cpu,
                                   "mem": snap.logical_simulation.mem},
            "device_simulation": _phones_from_proto(snap.device_simulation),
            "topology": json.loads(snap.topology_json or "{}"),
        }

    def get_cluster_available_resource(self):
        r = self._calls["getClusterAvailableResource"](empty_pb2.Empty())
        return {"cpu": r.cpu, "mem": r.mem}

    def get_cluster_total_resource(self):
        r = self._calls["getClusterTotalResource"](empty_pb2.Empty())
        return {"cpu": r.cpu, "mem": r.mem}

    def get_cluster_resource_detail(self):
        r = self._calls["getClusterResourceDetail"](empty_pb2.Empty())
        return json.loads(r.detail_json or "[]")

    def request_cluster_resource(self, task_id, user_id, cpu, mem) -> bool:
        return self._calls["requestClusterResource"](spb.ClusterResourceRequest(
            task_id=task_id, user_id=user_id, cpu=cpu, mem=mem)).is_success

    def release_cluster_resource(self, task_id) -> bool:
        return self._calls["releaseClusterResource"](
            spb.TaskRef(task_id=task_id)).is_success

    def request_phone_resource(self, task_id, user_id, phones) -> bool:
        return self._calls["requestPhoneResource"](spb.PhoneResourceRequest(
            task_id=task_id, user_id=user_id,
            phones=[spb.PhoneTypeCount(phone_type=t, num=n)
                    for t, n in phones.items()])).is_success

    def release_resource(self, task_id) -> bool:
        return self._calls["releaseResource"](spb.TaskRef(task_id=task_id)).is_success


# ----------------------------------------------------------------- DeviceFlow
class DeviceFlowServicer:
    """Adapter onto :class:`DeviceFlowService` (reference
    ``deviceflow_server.py:43`` surface, ``deviceflow.proto:63-72``)."""

    SERVICE_NAME = "olearning_sim_tpu.services.DeviceFlow"
    METHODS = {
        "RegisterTask": (spb.FlowRegisterRequest, spb.Ack),
        "UnRegisterTask": (spb.TaskRef, spb.Ack),
        "NotifyStart": (spb.FlowNotifyRequest, spb.Ack),
        "NotifyComplete": (spb.FlowNotifyRequest, spb.Ack),
        "PublishInbound": (spb.InboundMessage, spb.Ack),
        "GetTotalComputeResources": (spb.TaskRef, spb.FlowRegisterRequest),
        "CheckDeviceflowDispatchFinished": (spb.TaskRef, spb.Ack),
        "GetOutboundEndpoint": (empty_pb2.Empty, spb.OutboundEndpoint),
    }

    def __init__(self, service, outbound_endpoint: Dict[str, str] = None):
        self.service = service
        self.outbound_endpoint = outbound_endpoint or {
            "kind": "queue", "url": "inproc", "topic": "deviceflow_inbound",
        }

    def RegisterTask(self, request, context) -> spb.Ack:
        ok = self.service.register_task(
            request.task_id, list(request.total_compute_resources)
        )
        return spb.Ack(is_success=ok)

    def UnRegisterTask(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.service.unregister_task(request.task_id))

    def NotifyStart(self, request, context) -> spb.Ack:
        import json as _json

        outbound = None
        if request.outbound_service:
            try:
                outbound = _json.loads(request.outbound_service)
            except ValueError:
                return spb.Ack(
                    is_success=False, message="outbound_service not json"
                )
        ok, msg = self.service.notify_start(
            request.task_id, request.routing_key, request.compute_resource,
            request.strategy or "{}", outbound_service=outbound,
        )
        return spb.Ack(is_success=ok, message=msg or "")

    def NotifyComplete(self, request, context) -> spb.Ack:
        ok, msg = self.service.notify_complete(
            request.task_id, request.routing_key, request.compute_resource
        )
        return spb.Ack(is_success=ok, message=msg or "")

    def PublishInbound(self, request, context) -> spb.Ack:
        """Reference Pulsar inbound topic over gRPC: decode the JSON payload
        and drop it into the service's inbound room."""
        import json as _json

        try:
            payload = _json.loads(request.payload) if request.payload else None
        except ValueError:
            return spb.Ack(is_success=False, message="payload not json")
        self.service.publish(
            request.routing_key, request.compute_resource, payload
        )
        return spb.Ack(is_success=True)

    def GetTotalComputeResources(self, request, context) -> spb.FlowRegisterRequest:
        entry = self.service.registry.get(request.task_id) \
            if hasattr(self.service, "registry") else None
        resources = (entry or {}).get("total_compute_resources", [])
        return spb.FlowRegisterRequest(
            task_id=request.task_id, total_compute_resources=resources
        )

    def CheckDeviceflowDispatchFinished(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.service.check_dispatch_finished(request.task_id))

    def GetOutboundEndpoint(self, request, context) -> spb.OutboundEndpoint:
        return spb.OutboundEndpoint(**self.outbound_endpoint)


class DeviceFlowClient(_ClientBase):
    SERVICE = DeviceFlowServicer

    def register_task(self, task_id, total_compute_resources) -> bool:
        return self._calls["RegisterTask"](spb.FlowRegisterRequest(
            task_id=task_id,
            total_compute_resources=total_compute_resources)).is_success

    def unregister_task(self, task_id) -> bool:
        return self._calls["UnRegisterTask"](spb.TaskRef(task_id=task_id)).is_success

    def notify_start(self, task_id, routing_key, compute_resource,
                     strategy="{}", outbound_service=None):
        import json as _json

        ack = self._calls["NotifyStart"](spb.FlowNotifyRequest(
            task_id=task_id, routing_key=routing_key,
            compute_resource=compute_resource, strategy=strategy,
            outbound_service=(
                _json.dumps(outbound_service) if outbound_service else ""
            )))
        return ack.is_success, ack.message

    def notify_complete(self, task_id, routing_key, compute_resource):
        ack = self._calls["NotifyComplete"](spb.FlowNotifyRequest(
            task_id=task_id, routing_key=routing_key,
            compute_resource=compute_resource))
        return ack.is_success, ack.message

    def publish(self, routing_key, compute_resource, payload):
        """Duck-type-compatible with DeviceFlowService.publish — a runner
        wired to this client ships updates across processes (the reference's
        Pulsar publish, message_producer.py analogue)."""
        import json as _json

        ack = self._calls["PublishInbound"](spb.InboundMessage(
            routing_key=routing_key, compute_resource=compute_resource,
            payload=_json.dumps(payload)))
        if not ack.is_success:
            raise IOError(f"PublishInbound rejected: {ack.message}")

    def check_dispatch_finished(self, task_id) -> bool:
        return self._calls["CheckDeviceflowDispatchFinished"](
            spb.TaskRef(task_id=task_id)).is_success

    def get_outbound_endpoint(self):
        ep = self._calls["GetOutboundEndpoint"](empty_pb2.Empty())
        return {"kind": ep.kind, "url": ep.url, "topic": ep.topic}


# --------------------------------------------------------------- PhoneManager
class PhoneManagerServicer:
    """Adapter onto :class:`SimulatedPhoneFarm` (or a real phone-farm proxy)."""

    SERVICE_NAME = "olearning_sim_tpu.services.PhoneManager"
    METHODS = {
        "submitTask": (spb.DeviceJobRequest, spb.Ack),
        "getDeviceAvailableResource": (empty_pb2.Empty, spb.AllUsersPhoneResource),
        "requestDeviceResource": (spb.PhoneResourceRequest, spb.Ack),
        "releaseDeviceResource": (spb.TaskRef, spb.Ack),
        "stopDevice": (spb.TaskRef, spb.Ack),
        "getDeviceTaskStatus": (spb.TaskRef, spb.DeviceTaskResult),
    }

    def __init__(self, farm):
        self.farm = farm

    def submitTask(self, request, context) -> spb.Ack:
        ok = self.farm.submit_task(
            request.task_id, rounds=request.rounds,
            operators=list(request.operators),
            data=[{"name": d.name, "devices": list(d.device_types),
                   "nums": list(d.nums)} for d in request.data],
        )
        return spb.Ack(is_success=ok)

    def getDeviceAvailableResource(self, request, context) -> spb.AllUsersPhoneResource:
        return spb.AllUsersPhoneResource(
            users=_phones_to_proto(self.farm.get_device_available_resource())
        )

    def requestDeviceResource(self, request, context) -> spb.Ack:
        ok = self.farm.request_device_resource(
            request.task_id, request.user_id,
            {p.phone_type: p.num for p in request.phones},
        )
        return spb.Ack(is_success=ok)

    def releaseDeviceResource(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.farm.release_device_resource(request.task_id))

    def stopDevice(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.farm.stop_device(request.task_id))

    def getDeviceTaskStatus(self, request, context) -> spb.DeviceTaskResult:
        st = self.farm.get_device_task_status(request.task_id)
        return spb.DeviceTaskResult(
            is_finished=st["is_finished"],
            max_round=st.get("max_round", 0),
            round=st.get("round", 0),
            operator=st.get("operator", ""),
            device_data_status=[
                spb.DeviceDataStatus(
                    name=r["name"],
                    device_types=r["simulation_target"]["devices"],
                    success_num=r["simulation_target"]["success_num"],
                    failed_num=r["simulation_target"]["failed_num"],
                )
                for r in st.get("device_result", [])
            ],
        )


class PhoneManagerClient(_ClientBase):
    """Drop-in ``phone_client`` for TaskManager: same method names/shapes as
    :class:`SimulatedPhoneFarm`, over the wire."""

    SERVICE = PhoneManagerServicer

    def submit_task(self, task_id, rounds, operators, data) -> bool:
        return self._calls["submitTask"](spb.DeviceJobRequest(
            task_id=task_id, rounds=rounds, operators=operators,
            data=[spb.DeviceDataTarget(name=d["name"],
                                       device_types=d["devices"],
                                       nums=d["nums"]) for d in data],
        )).is_success

    def get_device_available_resource(self):
        return _phones_from_proto(
            self._calls["getDeviceAvailableResource"](empty_pb2.Empty()).users
        )

    def request_device_resource(self, task_id, user_id, phones) -> bool:
        return self._calls["requestDeviceResource"](spb.PhoneResourceRequest(
            task_id=task_id, user_id=user_id,
            phones=[spb.PhoneTypeCount(phone_type=t, num=n)
                    for t, n in phones.items()])).is_success

    def release_device_resource(self, task_id) -> bool:
        return self._calls["releaseDeviceResource"](
            spb.TaskRef(task_id=task_id)).is_success

    def stop_device(self, task_id) -> bool:
        return self._calls["stopDevice"](spb.TaskRef(task_id=task_id)).is_success

    def get_device_task_status(self, task_id):
        r = self._calls["getDeviceTaskStatus"](spb.TaskRef(task_id=task_id))
        return {
            "is_finished": r.is_finished,
            "max_round": r.max_round,
            "round": r.round,
            "operator": r.operator,
            "device_result": [
                {"name": s.name,
                 "simulation_target": {"devices": list(s.device_types),
                                       "success_num": list(s.success_num),
                                       "failed_num": list(s.failed_num)}}
                for s in r.device_data_status
            ],
        }


# ------------------------------------------------------------------- SliceMgr
class SliceMgrServicer:
    """Adapter onto :class:`ClusterManager` (TPU slice CRUD)."""

    SERVICE_NAME = "olearning_sim_tpu.services.SliceMgr"
    METHODS = {
        "createSlice": (spb.SliceCreateParam, spb.Ack),
        "modifySlice": (spb.SliceModifyParam, spb.Ack),
        "deleteSlice": (spb.SliceRef, spb.Ack),
        "querySlice": (spb.SliceRef, spb.SliceQueryResult),
    }

    def __init__(self, manager):
        self.manager = manager

    def createSlice(self, request, context) -> spb.Ack:
        try:
            self.manager.create_slice(request.slice_name, request.num_devices,
                                      request.user_id)
            return spb.Ack(is_success=True)
        except (ValueError, KeyError) as e:
            return spb.Ack(is_success=False, message=str(e))

    def modifySlice(self, request, context) -> spb.Ack:
        try:
            self.manager.modify_slice(request.slice_name, request.num_devices)
            return spb.Ack(is_success=True)
        except (ValueError, KeyError) as e:
            return spb.Ack(is_success=False, message=str(e))

    def deleteSlice(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.manager.delete_slice(request.slice_name))

    def querySlice(self, request, context) -> spb.SliceQueryResult:
        q = self.manager.query_slice(request.slice_name)
        return spb.SliceQueryResult(json_data=json.dumps(q) if q else "")


class SliceMgrClient(_ClientBase):
    SERVICE = SliceMgrServicer

    def create_slice(self, name, num_devices, user_id=""):
        ack = self._calls["createSlice"](spb.SliceCreateParam(
            slice_name=name, num_devices=num_devices, user_id=user_id))
        return ack.is_success, ack.message

    def modify_slice(self, name, num_devices):
        ack = self._calls["modifySlice"](spb.SliceModifyParam(
            slice_name=name, num_devices=num_devices))
        return ack.is_success, ack.message

    def delete_slice(self, name) -> bool:
        return self._calls["deleteSlice"](spb.SliceRef(slice_name=name)).is_success

    def query_slice(self, name):
        r = self._calls["querySlice"](spb.SliceRef(slice_name=name))
        return json.loads(r.json_data) if r.json_data else None


# ------------------------------------------------------------- PerformanceMgr
class PerformanceMgrServicer:
    SERVICE_NAME = "olearning_sim_tpu.services.PerformanceMgr"
    METHODS = {
        "getPerformance": (spb.TaskRef, spb.PerformanceReport),
        "getMetrics": (tpb.MetricsQuery, tpb.MetricsSnapshot),
        "startTrace": (spb.TraceRequest, spb.Ack),
        "stopTrace": (empty_pb2.Empty, spb.TraceRequest),
    }

    def __init__(self, manager):
        self.manager = manager

    def getPerformance(self, request, context) -> spb.PerformanceReport:
        return spb.PerformanceReport(
            json_data=json.dumps(self.manager.get_performance(request.task_id))
        )

    def getMetrics(self, request, context) -> tpb.MetricsSnapshot:
        """Live telemetry registry, rendered: Prometheus text exposition by
        default, JSON snapshot for ``format="json"``."""
        fmt = (request.format or "prometheus").lower()
        body = self.manager.render_metrics(fmt)
        ctype = ("application/json" if fmt in ("json", "snapshot")
                 else "text/plain; version=0.0.4; charset=utf-8")
        return tpb.MetricsSnapshot(content_type=ctype, body=body)

    def startTrace(self, request, context) -> spb.Ack:
        return spb.Ack(is_success=self.manager.start_trace(request.logdir))

    def stopTrace(self, request, context) -> spb.TraceRequest:
        return spb.TraceRequest(logdir=self.manager.stop_trace() or "")


class PerformanceMgrClient(_ClientBase):
    SERVICE = PerformanceMgrServicer

    def get_performance(self, task_id):
        r = self._calls["getPerformance"](spb.TaskRef(task_id=task_id))
        return json.loads(r.json_data)

    def get_metrics(self, fmt: str = "prometheus"):
        """Returns (content_type, rendered_body)."""
        r = self._calls["getMetrics"](tpb.MetricsQuery(format=fmt))
        return r.content_type, r.body

    def start_trace(self, logdir) -> bool:
        return self._calls["startTrace"](spb.TraceRequest(logdir=logdir)).is_success

    def stop_trace(self):
        return self._calls["stopTrace"](empty_pb2.Empty()).logdir or None

"""gRPC surfaces for the non-TaskMgr control-plane services, plus the
one-process session composer (reference ``simu_session.py:25-70``)."""

from olearning_sim_tpu.services.grpc_services import (
    DeviceFlowClient,
    DeviceFlowServicer,
    PerformanceMgrClient,
    PerformanceMgrServicer,
    PhoneManagerClient,
    PhoneManagerServicer,
    ResourceMgrClient,
    ResourceMgrServicer,
    SliceMgrClient,
    SliceMgrServicer,
    add_service_to_server,
)
from olearning_sim_tpu.services.session import SimulatorSession

__all__ = [
    "ResourceMgrServicer", "ResourceMgrClient",
    "DeviceFlowServicer", "DeviceFlowClient",
    "PhoneManagerServicer", "PhoneManagerClient",
    "SliceMgrServicer", "SliceMgrClient",
    "PerformanceMgrServicer", "PerformanceMgrClient",
    "add_service_to_server",
    "SimulatorSession",
]

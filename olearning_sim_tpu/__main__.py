"""``python -m olearning_sim_tpu --config platform.yaml`` — stand up the
full platform (the reference's per-service ``test/*/..._srv.py`` entry
points + ``config/config.conf`` wiring, as one command)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="olearning_sim_tpu",
        description="Boot the device-simulation platform from a config file.",
    )
    ap.add_argument("--config", required=True, help="platform YAML or INI file")
    ap.add_argument(
        "--print-port", action="store_true",
        help="print the bound gRPC port on stdout once serving",
    )
    ap.add_argument(
        "--platform", default=None,
        help="force the JAX platform (e.g. 'cpu' for control-plane-only "
        "hosts; some environments pin a hardware plugin via sitecustomize "
        "that plain env vars cannot override)",
    )
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from olearning_sim_tpu.config import session_from_file

    session = session_from_file(args.config)
    session.start()
    print(
        f"olearning_sim_tpu platform serving on port {session.port} "
        f"(services: {', '.join(session.services)})",
        file=sys.stderr,
    )
    if args.print_port:
        print(session.port, flush=True)

    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    stop.wait()
    session.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

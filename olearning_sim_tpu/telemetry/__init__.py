"""Unified telemetry: metrics registry, span tracing, exporters.

Three layers, all stdlib-only:

- :mod:`telemetry.metrics` — thread-safe ``Counter`` / ``Gauge`` /
  ``Histogram`` behind a :class:`MetricsRegistry` (process default +
  injectable instances);
- :mod:`telemetry.tracing` — :class:`SpanTracer` producing parent-linked
  wall-clock spans exportable as Chrome/Perfetto ``trace_event`` JSON (so
  runner spans open next to ``jax.profiler`` XLA traces);
- :mod:`telemetry.exporters` — Prometheus text exposition
  (:func:`render_prometheus` + :class:`MetricsHTTPServer`) and JSON
  snapshots (:func:`snapshot` / :func:`dump_json`) for bench artifacts.

Every platform metric is declared once in :data:`CATALOG` below and
materialized through :func:`instrument` — one definition point, so the
exporters, the docs metric table, and ``scripts/check_metrics.py`` (the
naming lint) can never drift from the instrumentation. Names follow
``ols_<subsystem>_<noun>_<unit>``; counters end in ``_total``.

Set ``OLS_TELEMETRY=0`` in the environment to start the process with the
default registry disabled (every mutation short-circuits to one attribute
check) — the bench's overhead baseline.
"""

from __future__ import annotations

import os
from typing import Optional

from olearning_sim_tpu.telemetry.metrics import (
    COUNTER,
    DEFAULT_BUCKETS,
    GAUGE,
    HISTOGRAM,
    Counter,
    Gauge,
    Histogram,
    Metric,
    MetricsRegistry,
    default_registry,
    set_default_registry,
)
from olearning_sim_tpu.telemetry.tracing import (
    Span,
    SpanTracer,
    default_tracer,
    set_default_tracer,
)
from olearning_sim_tpu.telemetry.exporters import (
    MetricsHTTPServer,
    dump_json,
    render_prometheus,
    snapshot,
)

# Round-phase latencies cluster well under a second on TPU but stretch to
# minutes for first-round compiles; checkpoint I/O sits in between.
_PHASE_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                  2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)
_IO_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
               10.0, 30.0, 60.0)
_DISPATCH_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                     1.0, 5.0)
# Simulated device time (completion/deadline): phone rounds span sub-second
# high-tier devices to many-minute stragglers.
_SIM_TIME_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0, 600.0, 1800.0)
# Median-normalized anomaly scores (dimensionless ratio): benign clients
# cluster near 1; sign-flip/scale attackers land decades above.
_ANOMALY_BUCKETS = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
# Staleness (server commits between a client's dispatch and its commit):
# async buffers keep most commits in the low single digits; the long tail
# is what max_staleness truncates.
_STALENESS_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

# name -> (kind, help, label names[, buckets]). THE metric catalog of
# record: docs/observability.md renders this table and the naming lint
# (scripts/check_metrics.py) validates it.
CATALOG = {
    # ------------------------------------------------------------- engine
    "ols_engine_round_phase_duration_seconds": (
        HISTOGRAM,
        "Wall-clock per round phase (select/train/host_transfer/eval/"
        "custom/accounting/checkpoint/model_export)",
        ("task_id", "operator", "phase"), _PHASE_BUCKETS,
    ),
    "ols_engine_round_duration_seconds": (
        HISTOGRAM,
        "Wall-clock per (round, operator) execution as recorded by "
        "PerformanceManager",
        ("task_id", "operator"), _PHASE_BUCKETS,
    ),
    "ols_engine_compile_duration_seconds": (
        GAUGE,
        "First-execution wall-clock of the compiled round step per "
        "(task, operator) — dominated by XLA compilation",
        ("task_id", "operator"),
    ),
    "ols_engine_rounds_total": (
        COUNTER,
        "Round executions by outcome (ok/failed/skipped)",
        ("task_id", "status"),
    ),
    "ols_engine_device_rounds_total": (
        COUNTER,
        "Virtual device-rounds advanced (clients x train rounds)",
        ("task_id",),
    ),
    "ols_engine_stragglers_total": (
        COUNTER,
        "Selected clients whose simulated completion missed the round "
        "deadline (deadline-masked aggregation; distinct from drops)",
        ("task_id",),
    ),
    "ols_engine_completion_time_seconds": (
        HISTOGRAM,
        "Simulated per-client completion times (network arrival + "
        "device-class compute) of each round's selected cohort",
        ("task_id",), _SIM_TIME_BUCKETS,
    ),
    "ols_engine_round_deadline_seconds": (
        HISTOGRAM,
        "Effective round deadline (static, adaptive-controller, or K-th "
        "arrival close) per train round",
        ("task_id",), _SIM_TIME_BUCKETS,
    ),
    "ols_engine_clipped_total": (
        COUNTER,
        "Participating clients whose delta L2 norm exceeded the defense "
        "clip threshold and was rescaled in-jit (adversarial-client "
        "defense)",
        ("task_id",),
    ),
    "ols_engine_anomaly_ratio": (
        HISTOGRAM,
        "Per-participant Krum-style anomaly scores normalized by the "
        "round's median score (benign clients cluster near 1; the flag "
        "threshold is defense.anomaly_threshold)",
        ("task_id",), _ANOMALY_BUCKETS,
    ),
    "ols_engine_quarantined_clients": (
        GAUGE,
        "Clients currently quarantined out of participation (strike "
        "budget exceeded via non-finite updates, anomaly flags, or "
        "operator preseed)",
        ("task_id",),
    ),
    "ols_engine_buffer_depth": (
        GAUGE,
        "Mean committed updates per async buffer commit in the last "
        "round (the buffer-utilization signal; the configured capacity "
        "is async.buffer_size)",
        ("task_id",),
    ),
    "ols_engine_staleness_rounds": (
        HISTOGRAM,
        "Per committed client update: server commits between its dispatch "
        "and its commit (async buffered rounds; the staleness-weight "
        "schedule discounts by this)",
        ("task_id",), _STALENESS_BUCKETS,
    ),
    "ols_engine_idle_seconds_total": (
        COUNTER,
        "Simulated seconds completed client updates spent waiting to be "
        "committed (mode=sync: until the round-close commit; mode=async: "
        "until their buffer filled) — the round-tail idle the async "
        "engine drives toward ~0",
        ("task_id", "mode"),
    ),
    "ols_engine_host_transfer_seconds_total": (
        COUNTER,
        "Wall seconds spent staging streamed cohort blocks host->device "
        "(FedCore.stream_round double-buffered placement; compare with "
        "round wall time for transfer exposure)",
        ("algorithm",),
    ),
    "ols_engine_stream_blocks_total": (
        COUNTER,
        "Cohort blocks executed by the streamed round engine (one "
        "compiled partial step per block; population / stream_block_rows "
        "per round)",
        ("algorithm",),
    ),
    "ols_engine_client_state_bytes": (
        GAUGE,
        "Host-resident persistent per-client state bytes held by the "
        "streamed population's HostClientStore (quarantine strikes, "
        "pacing EMAs, personalization state)",
        ("algorithm",),
    ),
    "ols_engine_eval_accuracy": (
        GAUGE,
        "Held-out eval accuracy of the global model at the last "
        "convergence-tracker eval point (fraction correct in [0, 1]; "
        "engine/convergence.py — the quality denominator behind every "
        "throughput number)",
        ("task_id",),
    ),
    "ols_engine_time_to_target_seconds": (
        GAUGE,
        "Seconds until eval accuracy first reached the configured "
        "convergence target, per clock (clock=sim: simulated fleet "
        "time; clock=wall: measured host time). Unset until the target "
        "is reached",
        ("task_id", "clock"),
    ),
    "ols_engine_rounds_to_target": (
        GAUGE,
        "Train rounds until eval accuracy first reached the configured "
        "convergence target (the rounds-denominated time-to-accuracy "
        "figure BENCH_convergence.json banks). Unset until reached",
        ("task_id",),
    ),
    "ols_engine_compile_cache_hits_total": (
        COUNTER,
        "Compiled executables deserialized from the persistent XLA "
        "compilation cache instead of recompiled (engine/compile_cache)",
        (),
    ),
    "ols_engine_compile_cache_misses_total": (
        COUNTER,
        "Executables compiled and written to the persistent XLA "
        "compilation cache (first compile of a round-program variant)",
        (),
    ),
    "ols_engine_tp_sharded_ratio": (
        GAUGE,
        "Fraction of parameter elements the mesh mp axis actually shards "
        "for a tensor-parallel build, per model (parallel/tp "
        "sharded_fraction; 0 means the model axis is pure replication — "
        "the tp_coverage analyzer fails mp>1 configs below 50%)",
        ("model",),
    ),
    "ols_engine_collective_bytes": (
        GAUGE,
        "Output bytes of the round program's dominant cross-replica "
        "collective per collective kind, from the lowered/compiled HLO "
        "(engine/hlo_stats; the aggregation-stage memory guard reads "
        "all-gather here)",
        ("program", "collective"),
    ),
    # ------------------------------------------------------------ fedcore
    "ols_fedcore_round_steps_total": (
        COUNTER,
        "Compiled FedCore round-step launches (train aggregation included)",
        ("algorithm",),
    ),
    "ols_fedcore_round_step_dispatch_seconds": (
        HISTOGRAM,
        "Host-side dispatch latency of the compiled round step (async "
        "launch, not device completion)",
        ("algorithm",), _DISPATCH_BUCKETS,
    ),
    # --------------------------------------------------------- checkpoint
    "ols_checkpoint_save_duration_seconds": (
        HISTOGRAM, "RoundCheckpointer.save wall-clock (dispatch side)",
        ("task_id",), _IO_BUCKETS,
    ),
    "ols_checkpoint_restore_duration_seconds": (
        HISTOGRAM, "RoundCheckpointer.restore wall-clock per attempted step",
        ("task_id",), _IO_BUCKETS,
    ),
    "ols_checkpoint_save_bytes_total": (
        COUNTER, "Payload bytes handed to checkpoint saves (leaf sizes)",
        ("task_id",),
    ),
    "ols_checkpoint_restore_bytes_total": (
        COUNTER, "Payload bytes restored from checkpoints (leaf sizes)",
        ("task_id",),
    ),
    # --------------------------------------------------------- deviceflow
    "ols_deviceflow_queue_depth": (
        GAUGE,
        "Staged messages by room (inbound queue / all shelves combined)",
        ("room",),
    ),
    "ols_deviceflow_inbound_messages_total": (
        COUNTER, "Messages published into the deviceflow inbound room", (),
    ),
    "ols_deviceflow_dispatched_messages_total": (
        COUNTER, "Messages delivered to outbound producers", (),
    ),
    "ols_deviceflow_dropped_messages_total": (
        COUNTER, "Messages dropped by dispatch behavior (drop schedule)", (),
    ),
    "ols_deviceflow_dispatch_batch_duration_seconds": (
        HISTOGRAM, "Outbound producer latency per dispatched batch",
        (), _DISPATCH_BUCKETS,
    ),
    "ols_deviceflow_parked_batches": (
        GAUGE,
        "Degraded outbound batches parked on durable shelves awaiting "
        "crash redelivery",
        (),
    ),
    # ------------------------------------------------------------ taskmgr
    "ols_taskmgr_state_transitions_total": (
        COUNTER, "Task status writes by destination state", ("status",),
    ),
    "ols_taskmgr_queue_depth": (
        GAUGE, "Tasks waiting in the scheduler queue", (),
    ),
    "ols_taskmgr_admission_rejected_total": (
        COUNTER,
        "Submissions refused by chip-pool admission control by reason "
        "(backpressure / oom / deadline); rejected tasks are failed "
        "loudly, never queued silently (taskmgr/pool.py)",
        ("reason",),
    ),
    "ols_taskmgr_task_wait_seconds": (
        HISTOGRAM,
        "Queue wait per launched task: submit accepted -> engine job "
        "launched (the p95 of this is the scheduler bench's figure of "
        "merit vs FIFO)",
        (), _PHASE_BUCKETS,
    ),
    "ols_taskmgr_pool_utilization_ratio": (
        GAUGE,
        "Fraction of a pool worker's peak-HBM capacity consumed by "
        "current placements (chip-pool scheduler ledger)",
        ("worker",),
    ),
    # --------------------------------------------------------- supervisor
    "ols_supervisor_resumes_total": (
        COUNTER,
        "Expired-lease RUNNING tasks re-adopted by the supervisor and "
        "relaunched through the checkpoint resume path",
        ("task_id",),
    ),
    "ols_supervisor_lease_age_seconds": (
        HISTOGRAM,
        "How long past expiry a reclaimed task's lease was when the "
        "supervisor took it (recovery latency; tune the lease TTL "
        "against this)",
        ("task_id",), _IO_BUCKETS,
    ),
    # --------------------------------------------------------- resilience
    "ols_resilience_events_total": (
        COUNTER,
        "Resilience events (retry/rollback/quarantine/...) mirrored from "
        "ResilienceLog",
        ("kind", "task_id"),
    ),
}


def instrument(name: str, registry: Optional[MetricsRegistry] = None):
    """Materialize a cataloged metric in ``registry`` (default registry when
    None). Idempotent; the only way platform code should create metrics."""
    spec = CATALOG[name]
    kind, help_text, labels = spec[0], spec[1], spec[2]
    registry = registry if registry is not None else default_registry()
    if kind == HISTOGRAM:
        buckets = spec[3] if len(spec) > 3 else DEFAULT_BUCKETS
        return registry.histogram(name, help_text, labels=labels,
                                  buckets=buckets)
    if kind == GAUGE:
        return registry.gauge(name, help_text, labels=labels)
    return registry.counter(name, help_text, labels=labels)


if os.environ.get("OLS_TELEMETRY") == "0":
    default_registry().enabled = False
    default_tracer().enabled = False

__all__ = [
    "CATALOG",
    "COUNTER",
    "GAUGE",
    "HISTOGRAM",
    "DEFAULT_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "Metric",
    "MetricsRegistry",
    "MetricsHTTPServer",
    "Span",
    "SpanTracer",
    "default_registry",
    "default_tracer",
    "dump_json",
    "instrument",
    "render_prometheus",
    "set_default_registry",
    "set_default_tracer",
    "snapshot",
]

"""Exporters: Prometheus text exposition, JSON snapshots, HTTP endpoint.

Everything stdlib: the scrape endpoint is a ``http.server`` on a daemon
thread (good enough for a per-host scrape target; production deployments can
front it with anything). The render format follows the Prometheus
text-exposition spec v0.0.4:

- ``# HELP`` / ``# TYPE`` per family;
- histograms render cumulative ``_bucket{le=...}`` series plus ``_sum`` /
  ``_count``; the ``+Inf`` bucket equals ``_count``;
- label values are escaped (backslash, double-quote, newline).

The JSON snapshot is the bench-artifact form: one dict per metric with kind,
labels, and values — stable keys so BENCH records diff cleanly across runs.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, List, Optional

from olearning_sim_tpu.telemetry.metrics import (
    COUNTER,
    GAUGE,
    HISTOGRAM,
    MetricsRegistry,
    default_registry,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(names, values, extra: Optional[List[tuple]] = None) -> str:
    pairs = [(n, v) for n, v in zip(names, values)]
    if extra:
        pairs += extra
    if not pairs:
        return ""
    body = ",".join(f'{n}="{_escape_label_value(str(v))}"' for n, v in pairs)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The full registry in Prometheus text-exposition format."""
    registry = registry if registry is not None else default_registry()
    lines: List[str] = []
    for metric in registry.metrics():
        if metric.help:
            lines.append(f"# HELP {metric.name} {metric.help}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        for key, child in metric.children():
            if metric.kind in (COUNTER, GAUGE):
                lines.append(
                    f"{metric.name}"
                    f"{_labels_str(metric.label_names, key)} "
                    f"{_fmt(child.value)}"
                )
            elif metric.kind == HISTOGRAM:
                for bound, cum in zip(child.bounds, child.cumulative()):
                    lines.append(
                        f"{metric.name}_bucket"
                        f"{_labels_str(metric.label_names, key, [('le', _fmt(bound))])} "
                        f"{cum}"
                    )
                lines.append(
                    f"{metric.name}_bucket"
                    f"{_labels_str(metric.label_names, key, [('le', '+Inf')])} "
                    f"{child.count}"
                )
                lines.append(
                    f"{metric.name}_sum"
                    f"{_labels_str(metric.label_names, key)} {_fmt(child.sum)}"
                )
                lines.append(
                    f"{metric.name}_count"
                    f"{_labels_str(metric.label_names, key)} {child.count}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot(registry: Optional[MetricsRegistry] = None) -> Dict[str, Any]:
    """JSON-ready dump of every instrument (bench.py artifact form)."""
    registry = registry if registry is not None else default_registry()
    out: Dict[str, Any] = {}
    for metric in registry.metrics():
        series = []
        for key, child in metric.children():
            labels = dict(zip(metric.label_names, key))
            if metric.kind == HISTOGRAM:
                series.append({
                    "labels": labels,
                    "count": child.count,
                    "sum": child.sum,
                    "buckets": {
                        _fmt(b): c
                        for b, c in zip(child.bounds, child.cumulative())
                    },
                })
            else:
                series.append({"labels": labels, "value": child.value})
        out[metric.name] = {
            "kind": metric.kind,
            "help": metric.help,
            "series": series,
        }
    return out


def dump_json(path: str, registry: Optional[MetricsRegistry] = None) -> str:
    """Write the JSON snapshot to ``path`` (bench artifacts); returns it."""
    import os

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(snapshot(registry), f, indent=1, sort_keys=True)
    return path


class MetricsHTTPServer:
    """Minimal scrape endpoint: ``GET /metrics`` (Prometheus text) and
    ``GET /metrics.json`` (snapshot) on a daemon thread.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``server.port`` after :meth:`start`.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        import http.server

        registry = self.registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = render_prometheus(registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = json.dumps(snapshot(registry)).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes are periodic
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ols-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsHTTPServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

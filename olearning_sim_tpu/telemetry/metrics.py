"""Metrics registry: thread-safe Counters / Gauges / Histograms.

The reference answers "what is my simulation doing" through
``PerformanceMgr.getPerformance`` backed by MySQL rows — end-of-run numbers,
one lens. This module is the always-on live layer underneath: every subsystem
registers named instruments here, and the exporters
(:mod:`olearning_sim_tpu.telemetry.exporters`) render one coherent snapshot
in Prometheus text-exposition or JSON form at any moment of a run.

Design constraints, in order:

- **Hot-path cost ~ a dict lookup + float add.** The round loop calls
  ``observe``/``inc`` thousands of times per second; no allocation beyond the
  first call per label set, no locking wider than one instrument. A disabled
  registry (``enabled=False``) reduces every mutation to one attribute check
  so the bench's registry-off baseline measures the true floor.
- **Process-global default plus injectable instances.** Deep call sites
  (a checkpointer three layers under the runner) use
  :func:`default_registry`; anything that wants isolation (tests, multi-task
  servers) passes its own :class:`MetricsRegistry`.
- **Fixed label schema per metric.** Label *names* are declared at
  registration; label *values* bind per call via :meth:`Metric.labels`.
  Unknown label names raise immediately — silent cardinality drift is how
  dashboards die.
- **Naming convention** ``ols_<subsystem>_<noun>_<unit>`` (checked by
  ``scripts/check_metrics.py``); counters additionally end in ``_total``.

No external dependencies: rendering stays in stdlib so the TPU image needs
nothing new.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Default histogram boundaries: wall-clock seconds from 100us to ~2min —
# covers per-batch dispatch latency through first-round XLA compiles.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


class _NullChild:
    """Returned by ``labels()`` on a disabled registry: every mutation is a
    no-op, so overhead-baseline runs skip even the child bookkeeping."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def observe_many(self, values) -> None:
        pass


_NULL_CHILD = _NullChild()


class Metric:
    """One named instrument: a family of children keyed by label values.

    An unlabeled metric has exactly one child (the ``()`` key); a labeled one
    materializes a child per distinct label-value tuple on first use.
    """

    kind: str = ""

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = (), registry=None):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._registry = registry
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not self.label_names:
            self._children[()] = self._new_child()

    # ------------------------------------------------------------- children
    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values: Any, **kv: Any):
        """Bind label values -> the child instrument. Accepts positional
        values in declared order, or keywords matching the declared names."""
        if not self._enabled:
            return _NULL_CHILD
        if kv:
            if values:
                raise ValueError(
                    f"{self.name}: pass label values positionally or by "
                    f"keyword, not both"
                )
            try:
                values = tuple(kv.pop(n) for n in self.label_names)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(declared: {self.label_names})"
                ) from None
            if kv:
                raise ValueError(
                    f"{self.name}: unknown labels {sorted(kv)} "
                    f"(declared: {list(self.label_names)})"
                )
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: got {len(values)} label values for "
                f"{len(self.label_names)} declared labels {self.label_names}"
            )
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _default_child(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} declares labels {self.label_names}; "
                f"call .labels(...) first"
            )
        return self._children[()]

    @property
    def _enabled(self) -> bool:
        return self._registry is None or self._registry.enabled

    def children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())

    def remove_children(self, **match: Any) -> int:
        """Drop children whose labels include ``match`` (e.g.
        ``task_id="t1"``); returns how many were removed. Prometheus
        scrapers treat a disappearing series as a counter reset."""
        want = {k: str(v) for k, v in match.items()}
        idx = {n: i for i, n in enumerate(self.label_names)}
        unknown = set(want) - set(idx)
        if unknown:
            raise ValueError(
                f"{self.name}: unknown labels {sorted(unknown)} "
                f"(declared: {list(self.label_names)})"
            )
        with self._lock:
            doomed = [
                key for key in self._children
                if key and all(key[idx[k]] == v for k, v in want.items())
            ]
            for key in doomed:
                del self._children[key]
            return len(doomed)


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        # Locked: `+=` is a read-modify-write across bytecodes, and counters
        # are hit from gRPC worker and dispatcher threads concurrently.
        with self._lock:
            self.value += amount


class Counter(Metric):
    kind = COUNTER

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        if self._enabled:
            self._default_child().inc(amount)

    def labels(self, *values: Any, **kv: Any) -> "_CounterChild":
        return super().labels(*values, **kv)


class _GaugeChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        self.value = float(value)  # plain store: atomic under the GIL

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class Gauge(Metric):
    kind = GAUGE

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        if self._enabled:
            self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        if self._enabled:
            self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        if self._enabled:
            self._default_child().dec(amount)

    def labels(self, *values: Any, **kv: Any) -> "_GaugeChild":
        return super().labels(*values, **kv)


class _HistogramChild:
    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = bounds
        # counts[i] is observations <= bounds[i]; the implicit +Inf bucket is
        # ``count`` itself (cumulative form is materialized at render time).
        self.counts = [0] * len(bounds)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.sum += value
            self.count += 1
            if i < len(self.bounds):
                self.counts[i] += 1

    def observe_many(self, values) -> None:
        """Bulk observation: one lock acquisition and one vectorized
        bucketing for a whole array (the per-client completion-time path
        observes thousands of samples per round — a Python loop of
        ``observe`` calls there would tax the round loop)."""
        import numpy as np

        arr = np.asarray(values, float).ravel()
        if arr.size == 0:
            return
        idx = np.searchsorted(self.bounds, arr, side="left")
        binned = np.bincount(idx, minlength=len(self.bounds) + 1)
        with self._lock:
            self.sum += float(arr.sum())
            self.count += int(arr.size)
            for i, c in enumerate(binned[:len(self.bounds)]):
                if c:
                    self.counts[i] += int(c)

    def cumulative(self) -> List[int]:
        """Per-bucket cumulative counts (Prometheus ``le`` semantics),
        excluding +Inf (which is ``count``)."""
        with self._lock:
            counts = list(self.counts)
        out, acc = [], 0
        for c in counts:
            acc += c
            out.append(acc)
        return out


class Histogram(Metric):
    kind = HISTOGRAM

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS, registry=None):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        if any(math.isinf(b) for b in bounds):
            # +Inf is implicit; an explicit one would double-render.
            bounds = tuple(b for b in bounds if not math.isinf(b))
        self.buckets = bounds
        super().__init__(name, help, label_names, registry=registry)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        if self._enabled:
            self._default_child().observe(value)

    def observe_many(self, values) -> None:
        if self._enabled:
            self._default_child().observe_many(values)

    def labels(self, *values: Any, **kv: Any) -> "_HistogramChild":
        return super().labels(*values, **kv)


_KINDS = {COUNTER: Counter, GAUGE: Gauge, HISTOGRAM: Histogram}


class MetricsRegistry:
    """Thread-safe name -> Metric map with idempotent registration.

    Re-registering the same (name, kind, labels) returns the existing
    instrument — modules register at import/constructor time and several
    components share one process registry. A name collision with a
    *different* schema raises: two meanings for one name is the lie no
    exporter can render.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    # --------------------------------------------------------- registration
    def _register(self, kind: str, name: str, help: str,
                  label_names: Sequence[str], **kw) -> Metric:
        label_names = tuple(label_names)
        # Lock-free fast path: instrument() runs per metric event on hot
        # paths (publishes, dispatched batches, status writes), and dict
        # reads are atomic under the GIL — only genuine registration takes
        # the registry lock.
        existing = self._metrics.get(name)
        if existing is None:
            with self._lock:
                existing = self._metrics.get(name)
                if existing is None:
                    metric = _KINDS[kind](name, help, label_names,
                                          registry=self, **kw)
                    self._metrics[name] = metric
                    return metric
        if existing.kind != kind or existing.label_names != label_names:
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{existing.kind}{existing.label_names}, "
                f"requested {kind}{label_names}"
            )
        return existing

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._register(COUNTER, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(GAUGE, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(HISTOGRAM, name, help, labels, buckets=buckets)

    # --------------------------------------------------------------- access
    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def unregister(self, name: str) -> bool:
        with self._lock:
            return self._metrics.pop(name, None) is not None

    def retire_label_value(self, label_name: str, value: Any) -> int:
        """Drop every child series carrying ``label_name=value`` across all
        metrics — the retention lever for per-task labels in long-lived
        processes (call with ``("task_id", task_id)`` once a task's series
        no longer need scraping). Returns the number of series removed."""
        removed = 0
        for metric in self.metrics():
            if label_name in metric.label_names:
                removed += metric.remove_children(**{label_name: value})
        return removed

    def clear(self) -> None:
        """Drop every instrument (tests); registrants re-create on next use."""
        with self._lock:
            self._metrics.clear()


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide default sink (what instrumented modules use when no
    registry is injected)."""
    return _DEFAULT


def set_default_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (tests / embedding apps); returns the old
    one so callers can restore it."""
    global _DEFAULT
    old, _DEFAULT = _DEFAULT, registry
    return old

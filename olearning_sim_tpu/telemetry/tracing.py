"""Span tracer: causal, parent-linked wall-clock spans -> Perfetto JSON.

``jax.profiler`` answers "what did XLA do" at op granularity; this module
answers "what did the *runner* do" — which round, which operator, which
phase — at host granularity. Both export to the same Chrome ``trace_event``
JSON format, so a runner-span file opens in Perfetto/chrome://tracing right
next to the XLA timeline (and ``PerformanceManager.stop_trace`` writes one
beside every captured XLA trace).

Usage::

    tracer = SpanTracer()            # or default_tracer()
    with tracer.span("round.train", round_idx=3, operator="train"):
        ...                          # nested spans parent-link automatically

Spans carry monotonic wall-clock durations, a per-tracer span id, the
enclosing span's id (``parent_id``), and free-form attributes rendered as
trace-event ``args``. Nesting is tracked per thread (a contextvar-free
``threading.local`` stack — spans never cross threads, matching the
trace_event ``B``/``E`` model Perfetto reconstructs per tid).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    span_id: int
    parent_id: Optional[int]
    start_s: float          # monotonic start (tracer epoch-relative)
    duration_s: float = 0.0
    thread_id: int = 0
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_trace_event(self) -> Dict[str, Any]:
        """Chrome trace_event complete-event (``ph: X``) form; timestamps in
        microseconds per the spec."""
        args = {k: v for k, v in self.attrs.items()}
        args["span_id"] = self.span_id
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        return {
            "name": self.name,
            "ph": "X",
            "cat": "runner",
            "ts": round(self.start_s * 1e6, 3),
            "dur": round(self.duration_s * 1e6, 3),
            "pid": os.getpid(),
            "tid": self.thread_id,
            "args": args,
        }


class _ActiveSpan:
    def __init__(self, tracer: "SpanTracer", span: Span):
        self._tracer = tracer
        self.span = span
        self._t0 = 0.0

    def __enter__(self) -> Span:
        self._t0 = time.perf_counter()
        self._tracer._stack().append(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        self.span.duration_s = time.perf_counter() - self._t0
        if exc_type is not None:
            self.span.attrs["error"] = f"{exc_type.__name__}: {str(exc)[:200]}"
        stack = self._tracer._stack()
        if stack and stack[-1] is self.span:
            stack.pop()
        self._tracer._finish(self.span)
        return False


class _NullSpanCtx:
    """Returned by a disabled tracer: zero bookkeeping, reusable."""

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullSpanCtx()


class SpanTracer:
    """Thread-safe span recorder with a bounded finished-span window.

    ``keep_last`` bounds memory for long runs (structured forensics keep the
    tail; exported files should be flushed per run/trace window anyway).
    """

    def __init__(self, keep_last: int = 65536, enabled: bool = True):
        self.keep_last = keep_last
        self.enabled = enabled
        self._lock = threading.Lock()
        self._local = threading.local()
        self._spans: List[Span] = []
        self._next_id = 1
        self._epoch = time.perf_counter()

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs: Any):
        """``with tracer.span("round.train", round_idx=3): ...`` — opens a
        span parented to the innermost open span on this thread."""
        if not self.enabled:
            return _NULL_CTX
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        return _ActiveSpan(self, Span(
            name=name, span_id=span_id, parent_id=parent,
            start_s=time.perf_counter() - self._epoch,
            thread_id=threading.get_ident() & 0x7FFFFFFF,
            attrs=dict(attrs),
        ))

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.keep_last:
                del self._spans[: len(self._spans) - self.keep_last]

    # ---------------------------------------------------------------- reads
    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def now(self) -> float:
        """Tracer-relative clock (same scale as ``Span.start_s``) — a
        watermark for windowed exports."""
        return time.perf_counter() - self._epoch

    # --------------------------------------------------------------- export
    def to_trace_events(
        self, since_s: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """``since_s`` (tracer-relative, from :meth:`now`) limits the export
        to spans started after the watermark — e.g. only the spans inside
        one XLA trace window, not the whole process history."""
        return [
            s.to_trace_event() for s in self.spans()
            if since_s is None or s.start_s >= since_s
        ]

    def to_perfetto_json(self, since_s: Optional[float] = None) -> str:
        """Chrome/Perfetto ``trace_event`` JSON (object form with
        ``traceEvents``, the shape both UIs and TensorBoard accept)."""
        return json.dumps({
            "traceEvents": self.to_trace_events(since_s),
            "displayTimeUnit": "ms",
        })

    def export(self, path: str, since_s: Optional[float] = None) -> str:
        """Write the Perfetto JSON next to (typically) an XLA trace dir;
        returns ``path``. Parent directories are created."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_perfetto_json(since_s))
        return path


_DEFAULT = SpanTracer()


def default_tracer() -> SpanTracer:
    """The process-wide tracer (what instrumented modules use when no tracer
    is injected)."""
    return _DEFAULT


def set_default_tracer(tracer: SpanTracer) -> SpanTracer:
    global _DEFAULT
    old, _DEFAULT = _DEFAULT, tracer
    return old
